"""serve.py CLI wiring (CLAUDE.md blind spot: every shipped CLI capability
must be reachable and booted by a test, or it rots silently)."""

import pytest

pytest.importorskip("jax")

MODEL = ["--d-model", "32", "--n-heads", "4", "--n-layers", "2",
         "--d-ff", "64", "--vocab-size", "64"]


def run_serve(args, capsys):
    from hivedscheduler_tpu import serve

    rc = serve.main(args)
    return rc, capsys.readouterr().out


def test_basic_run_emits_all_requests(capsys):
    rc, out = run_serve(MODEL + ["--requests", "3", "--max-batch", "2",
                                 "--max-len", "64", "--max-new-tokens", "4"],
                        capsys)
    assert rc == 0
    lines = [l for l in out.splitlines() if l.startswith("[")]
    assert len(lines) == 3
    assert all(len(l.split()) >= 2 for l in lines)  # every request got tokens


def test_decode_steps_run(capsys):
    """--decode-steps must reach the engine (recurring blind spot): the
    fused windows execute and every request still completes."""
    rc, out = run_serve(MODEL + ["--requests", "3", "--max-batch", "2",
                                 "--max-len", "64", "--max-new-tokens", "6",
                                 "--decode-steps", "4",
                                 "--arrival-every", "0"],
                        capsys)
    assert rc == 0
    lines = [l for l in out.splitlines() if l.startswith("[")]
    assert len(lines) == 3


def test_prefix_cache_run(capsys):
    rc, out = run_serve(
        MODEL + ["--requests", "4", "--max-batch", "2", "--max-len", "96",
                 "--max-new-tokens", "4", "--prefix-cache", "8",
                 "--system-prompt-len", "24"],
        capsys,
    )
    assert rc == 0


def test_prefix_cache_overflow_fails_fast(capsys):
    from hivedscheduler_tpu import serve

    with pytest.raises(SystemExit):
        serve.main(MODEL + ["--prefix-cache", "8", "--max-len", "32"])


@pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
def test_lora_checkpoint_serves(tmp_path, capsys):
    """A LoRA fine-tune checkpoint restores into the engine with adapters
    merged (the generate.py path, mirrored)."""
    from hivedscheduler_tpu import train

    ck = str(tmp_path / "ck")
    assert train.main(
        ["--steps", "2", "--lora-rank", "4", "--seq-len", "32",
         "--batch", "2", "--tp", "2", "--sp", "2", "--checkpoint-dir", ck,
         "--checkpoint-every", "100", "--log-every", "100",
         "--d-model", "32", "--n-heads", "4", "--n-layers", "2",
         "--d-ff", "64", "--vocab-size", "64"]
    ) in (0, None)
    rc, out = run_serve(
        MODEL + ["--requests", "2", "--max-batch", "2", "--max-len", "64",
                 "--max-new-tokens", "4", "--lora-rank", "4",
                 "--checkpoint-dir", ck],
        capsys,
    )
    assert rc == 0
    assert len([l for l in out.splitlines() if l.startswith("[")]) == 2


def test_paged_kv_run(capsys):
    """--page-size/--num-blocks must reach the engine (recurring blind
    spot): the paged allocator serves the whole load."""
    from hivedscheduler_tpu import serve as serve_mod  # noqa: F401

    rc, out = run_serve(
        MODEL + ["--requests", "4", "--max-batch", "2", "--max-len",
                 "64", "--max-new-tokens", "4", "--page-size", "8",
                 "--num-blocks", "17", "--arrival-every", "0"],
        capsys,
    )
    assert rc == 0
    lines = [l for l in out.splitlines() if l.startswith("[")]
    assert len(lines) == 4
    assert all(len(l.split()) >= 2 for l in lines)


def test_paged_num_blocks_too_small_fails_fast(capsys):
    rc, _ = run_serve(MODEL + ["--requests", "1", "--max-len", "64",
                               "--page-size", "8", "--num-blocks", "4"],
                      capsys)
    assert rc == 1  # engine ValueError surfaces as the CLI error path


@pytest.mark.slow  # tier-1 wall-time budget: the fleet-config run below boots the same fleet/disaggregate/autoscale path from the yaml
def test_fleet_disaggregated_run(capsys):
    """--fleet/--disaggregate must reach the router (recurring blind
    spot): every request is served through the fleet, printed as [fid]
    lines."""
    rc, out = run_serve(
        MODEL + ["--requests", "3", "--max-batch", "2", "--max-len", "64",
                 "--max-new-tokens", "4", "--fleet", "3", "--disaggregate",
                 "--page-size", "8", "--route-policy", "prefix_affinity",
                 "--arrival-every", "0"],
        capsys,
    )
    assert rc == 0
    lines = [l for l in out.splitlines() if l.startswith("[")]
    assert len(lines) == 3
    assert all(len(l.split()) >= 2 for l in lines)


def test_fleet_config_yaml_drives_the_fleet(capsys):
    """The shipped fleet.yaml's `fleet:` section must be consumable by
    the CLI (shipped artifacts rot silently unless booted). The fixture
    sets replicas/disaggregate/prefix_affinity/autoscale, so ONE run
    boots the whole --fleet surface (tier-1 wall-time budget rule; the
    explicit-flag variant rides the slow tier)."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "example", "config", "design",
        "fleet.yaml")
    rc, out = run_serve(
        MODEL + ["--requests", "2", "--max-batch", "2", "--max-len", "64",
                 "--max-new-tokens", "3", "--fleet-config", path,
                 "--arrival-every", "0"],
        capsys,
    )
    assert rc == 0
    assert len([l for l in out.splitlines() if l.startswith("[")]) == 2


def test_fleet_disaggregate_needs_both_roles(capsys):
    from hivedscheduler_tpu import serve

    with pytest.raises(SystemExit):
        serve.main(MODEL + ["--fleet", "1", "--disaggregate"])


def test_spec_decode_flag_routes_first_class(capsys):
    """--spec-decode constructs through ServingEngine(spec_decode=...) and
    composes with --page-size in one run."""
    rc, out = run_serve(
        MODEL + ["--requests", "3", "--max-batch", "2", "--max-len",
                 "64", "--max-new-tokens", "4", "--spec-decode",
                 "--gamma", "2", "--page-size", "8",
                 "--arrival-every", "0"],
        capsys,
    )
    assert rc == 0
    lines = [l for l in out.splitlines() if l.startswith("[")]
    assert len(lines) == 3
