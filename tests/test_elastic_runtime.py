"""Scheduler-side elastic offers (ISSUE 10): a waiting elastic gang whose
full shape is blocked (and whose wait the defrag planner declined to fix)
is bound onto the largest feasible shrink from its declared ladder; once
capacity frees, the degraded gang is grow-migrated back to full shape via
the PR 9 migration machinery (reserve target -> evict/checkpoint ->
re-place -> resume). Plus the duration-aware guaranteed backfill arm:
a gang declaring ``durationSeconds`` may ride a reserved hole when it
provably finishes before the hold expires.

Scenario fixture mirrors tests/test_defrag_runtime.py: the mini 2-cell
cluster where one 4-chip cell is taken and an 8-chip elastic gang cannot
fit at full shape.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tests.test_defrag import make_pod, mini_config  # noqa: E402,F401
from tests.test_defrag_runtime import build_scheduler, check, drive  # noqa: E402

from hivedscheduler_tpu.api import constants as C  # noqa: E402
from hivedscheduler_tpu.chaos import invariants  # noqa: E402,F401
from hivedscheduler_tpu.common.utils import to_json  # noqa: E402
from hivedscheduler_tpu.defrag.probe import GangSpec, shrink_ladder  # noqa: E402
from hivedscheduler_tpu.k8s.types import Container, Pod  # noqa: E402
from hivedscheduler_tpu.runtime.metrics import REGISTRY  # noqa: E402


def make_elastic_pods(group, pods, chips, min_chips, vc="vc-x", prio=5,
                      duration=0):
    spec = {
        "virtualCluster": vc, "priority": prio,
        "leafCellType": "v5p-chip", "leafCellNumber": chips,
        "elasticMinChips": min_chips,
        "affinityGroup": {
            "name": group,
            "members": [{"podNumber": pods, "leafCellNumber": chips}],
        },
    }
    if duration:
        spec["durationSeconds"] = duration
    return [
        Pod(name=f"{group}-{i}", uid=f"{group}-{i}",
            annotations={C.ANNOTATION_POD_SCHEDULING_SPEC: to_json(spec)},
            containers=[Container(resource_limits={
                C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})])
        for i in range(pods)
    ]


def blocked_elastic_scheduler():
    """g1 holds one of the two 4-chip cells; elastic gang e (2 pods x 4
    chips = 8, floor 2) cannot fit at full shape."""
    sched, kube, nodes = build_scheduler()
    assert drive(sched, kube, nodes, make_pod("g1-0", "g1", 4)) is not None
    pods = make_elastic_pods("e", 2, 4, 2)
    for p in pods:
        assert drive(sched, kube, nodes, p) is None
    return sched, kube, nodes


class TestShrinkLadder:
    def test_halving_rungs_to_the_floor(self):
        spec = GangSpec(name="e", vc="v", priority=5, leaf_cell_type="c",
                        members=((2, 4),), elastic_min_chips=2)
        rungs = shrink_ladder(spec)
        assert [r.members for r in rungs] == [((2, 2),), ((2, 1),)]
        assert all(r.elastic_full_members == ((2, 4),) for r in rungs)
        assert all(r.degraded for r in rungs)
        assert rungs[0].full_spec().members == ((2, 4),)

    def test_floor_respected(self):
        spec = GangSpec(name="e", vc="v", priority=5, leaf_cell_type="c",
                        members=((2, 4),), elastic_min_chips=5)
        assert shrink_ladder(spec) == []

    def test_non_elastic_has_no_ladder(self):
        spec = GangSpec(name="e", vc="v", priority=5, leaf_cell_type="c",
                        members=((2, 4),))
        assert shrink_ladder(spec) == []

    def test_odd_shapes_stop_the_ladder(self):
        spec = GangSpec(name="e", vc="v", priority=5, leaf_cell_type="c",
                        members=((1, 6),), elastic_min_chips=1)
        assert [r.members for r in shrink_ladder(spec)] == [((1, 3),)]


class TestShrinkOffer:
    def test_offer_binds_the_largest_feasible_rung(self):
        sched, kube, nodes = blocked_elastic_scheduler()
        tick = sched.defrag_tick()
        assert tick["planned"] is None  # the defrag planner declined
        offer = tick["elasticOffer"]
        assert offer is not None
        assert offer["group"] == "e"
        assert offer["offeredChips"] == 4 and offer["fullChips"] == 8
        check(sched, "post-offer")
        # the degraded incarnation is BOUND and carries the full shape in
        # its own annotations (crash-safe grow eligibility) plus a 2-chip
        # isolation handoff — the offered slice the workload reads
        st = sched.get_defrag_status()
        assert st["elasticDegraded"] == {
            "e": {"offeredChips": 4, "fullChips": 8}}
        g = sched.scheduler_algorithm.get_affinity_group("e")
        total = sum(len(v) for v in g.status.physical_placement.values())
        assert total == 4
        bound = [st_.pod for st_ in sched.pod_schedule_statuses.values()
                 if st_.pod is not None and st_.pod.name.startswith("el")]
        assert len(bound) == 2
        for p in bound:
            spec = GangSpec.from_pod(p)
            assert spec.degraded and spec.full_spec().chips == 8
            iso = p.annotations[C.ANNOTATION_POD_CHIP_ISOLATION]
            assert len(iso.split(",")) == 2
        assert ('tpu_hive_elastic_offers_total{outcome="offered"}'
                in REGISTRY.render())

    def test_floor_blocks_too_deep_shrinks(self):
        sched, kube, nodes = build_scheduler()
        assert drive(sched, kube, nodes, make_pod("g1-0", "g1", 4)) is not None
        # floor 8 == full shape: no rung exists, the gang keeps waiting
        for p in make_elastic_pods("e8", 2, 4, 8):
            assert drive(sched, kube, nodes, p) is None
        tick = sched.defrag_tick()
        assert tick["elasticOffer"] is None
        assert "e8" in sched.get_defrag_status()["waiters"]

    def test_non_elastic_waiter_is_untouched(self):
        sched, kube, nodes = build_scheduler()
        assert drive(sched, kube, nodes, make_pod("g1-0", "g1", 4)) is not None
        w = make_pod("w-0", "w", 4, pods=2)
        assert drive(sched, kube, nodes, w) is None
        tick = sched.defrag_tick()
        assert tick["elasticOffer"] is None
        assert "w" in sched.get_defrag_status()["waiters"]

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("HIVED_ELASTIC", "0")
        sched, kube, nodes = blocked_elastic_scheduler()
        tick = sched.defrag_tick()
        assert tick["elasticOffer"] is None and tick["elasticGrows"] == []
        assert "e" in sched.get_defrag_status()["waiters"]

    def test_no_offers_while_nodes_bad(self):
        from hivedscheduler_tpu.k8s.types import Node, NodeCondition

        sched, kube, nodes = blocked_elastic_scheduler()
        sched._update_node(
            Node(name=nodes[0]),
            Node(name=nodes[0],
                 conditions=[NodeCondition(type="Ready", status="False")]),
        )
        tick = sched.defrag_tick()
        assert tick["elasticOffer"] is None


class TestGrowPromote:
    def grown(self):
        sched, kube, nodes = blocked_elastic_scheduler()
        assert sched.defrag_tick()["elasticOffer"] is not None
        kube.delete_pod("default", "g1-0")  # capacity frees
        return sched, kube, nodes

    def test_grow_migrates_back_to_full_shape(self):
        sched, kube, nodes = self.grown()
        tick = sched.defrag_tick()
        grows = tick["elasticGrows"]
        assert grows and grows[0]["group"] == "e"
        assert grows[0]["fromChips"] == 4 and grows[0]["toChips"] == 8
        # the grow rides the migration machinery: reservation on the
        # target, eviction issued; the next pass re-binds at full shape
        rep = sched.resume_migrations()
        assert rep[grows[0]["migrationId"]]["state"] == "Done"
        check(sched, "post-grow")
        g = sched.scheduler_algorithm.get_affinity_group("e")
        total = sum(len(v) for v in g.status.physical_placement.values())
        assert total == 8
        st = sched.get_defrag_status()
        assert st["elasticDegraded"] == {} and st["reservations"] == []
        # the grown pods carry no degraded marker any more
        for st_ in sched.pod_schedule_statuses.values():
            spec = GangSpec.from_pod(st_.pod)
            if spec.name == "e":
                assert not spec.degraded and spec.elastic_min_chips == 2
        assert ('tpu_hive_elastic_grows_total{outcome="completed"}'
                in REGISTRY.render())

    def test_no_grow_while_capacity_is_still_used(self):
        sched, kube, nodes = blocked_elastic_scheduler()
        assert sched.defrag_tick()["elasticOffer"] is not None
        tick = sched.defrag_tick()  # g1 still holds the other cell
        assert tick["elasticGrows"] == []
        st = sched.get_defrag_status()
        assert st["elasticDegraded"] != {}

    def test_degraded_record_cleared_when_gang_deleted(self):
        sched, kube, nodes = blocked_elastic_scheduler()
        assert sched.defrag_tick()["elasticOffer"] is not None
        for st_ in list(sched.pod_schedule_statuses.values()):
            if GangSpec.from_pod(st_.pod).name == "e":
                kube.delete_pod(st_.pod.namespace, st_.pod.name)
        assert sched.get_defrag_status()["elasticDegraded"] == {}


class TestDurationAwareBackfill:
    def reserved(self):
        """A waiter holds a reservation (via the migration pipeline of
        tests/test_defrag_runtime.fragmented_scheduler)."""
        from tests.test_defrag_runtime import fragmented_scheduler

        sched, kube, nodes = fragmented_scheduler()
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        sched.resume_migrations()
        return sched, kube, nodes, w, plan

    def test_short_guaranteed_gang_rides_the_hold(self):
        sched, kube, nodes, w, plan = self.reserved()
        # declares it finishes in 1s; the hold's TTL is 300s: fits-window
        rider = make_elastic_pods("rider", 1, 4, 0, duration=1.0)[0]
        assert drive(sched, kube, nodes, rider) is not None
        assert ('tpu_hive_backfill_admissions_total{outcome="fits-window"}'
                in REGISTRY.render())
        check(sched, "rider-landed")

    def test_long_guaranteed_gang_stays_blocked(self):
        sched, kube, nodes, w, plan = self.reserved()
        # a declared duration past the hold's TTL cannot ride
        rider = make_elastic_pods("slow-rider", 1, 4, 0,
                                  duration=10_000.0)[0]
        assert drive(sched, kube, nodes, rider) is None
        blocked = REGISTRY.render()
        assert 'tpu_hive_backfill_admissions_total{outcome="blocked"}' in blocked
        # the holder still lands in its reserved slice
        assert drive(sched, kube, nodes, w) in plan["waiterNodes"]
        check(sched, "end")

    def test_unknown_duration_keeps_conservative_behavior(self):
        sched, kube, nodes, w, plan = self.reserved()
        rider = make_pod("nodur-0", "nodur", 4)
        assert drive(sched, kube, nodes, rider) is None


class TestSpecValidation:
    def test_negative_duration_rejected(self):
        from hivedscheduler_tpu.api.types import WebServerError
        from hivedscheduler_tpu.runtime import utils as internal_utils

        pod = make_elastic_pods("bad", 1, 4, 0, duration=-1.0)[0]
        with pytest.raises(WebServerError, match="durationSeconds is negative"):
            internal_utils.extract_pod_scheduling_spec(pod)

    def test_elastic_min_above_total_rejected(self):
        from hivedscheduler_tpu.api.types import WebServerError
        from hivedscheduler_tpu.runtime import utils as internal_utils

        pod = make_elastic_pods("bad2", 1, 4, 99)[0]
        with pytest.raises(WebServerError,
                           match="elasticMinChips exceeds the"):
            internal_utils.extract_pod_scheduling_spec(pod)

    def test_spec_roundtrip_keeps_elastic_fields(self):
        from hivedscheduler_tpu.api.types import PodSchedulingSpec

        d = {
            "virtualCluster": "v", "priority": 1, "leafCellType": "c",
            "leafCellNumber": 4, "durationSeconds": 60.0,
            "elasticMinChips": 2,
            "elasticFullMembers": [{"podNumber": 2, "leafCellNumber": 4}],
            "affinityGroup": {"name": "g", "members": [
                {"podNumber": 2, "leafCellNumber": 4}]},
        }
        spec = PodSchedulingSpec.from_dict(d)
        out = spec.to_dict()
        assert out["durationSeconds"] == 60.0
        assert out["elasticMinChips"] == 2
        assert out["elasticFullMembers"] == [
            {"podNumber": 2, "leafCellNumber": 4}]
