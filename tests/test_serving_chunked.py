"""Chunked prefill in the continuous-batching engine (prefill_chunk > 0).

Chunking must be a pure scheduling change: the chunks write exactly the KV
a monolithic prefill would, so every stream matches the unchunked engine
bit-for-bit, while each engine step runs at most one bounded chunk — a
long prompt can no longer stall the decoding rows for its whole prefill."""

import pytest

pytest.importorskip("jax")

import jax

from hivedscheduler_tpu.models import transformer as tm
from hivedscheduler_tpu.models.serving import ServingEngine


def tiny_cfg(**kw):
    base = dict(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=128, max_seq_len=128)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    params = tm.cast_params(tm.init_params(cfg, jax.random.PRNGKey(0)),
                            cfg.dtype)
    return cfg, params


LONG = list(range(20, 60))  # 40-token prompt


def run_all(cfg, params, prompts, budget=5, **kw):
    eng = ServingEngine(params, cfg, max_batch=2, max_len=96, **kw)
    reqs = [eng.submit(p, budget) for p in prompts]
    eng.run_until_drained()
    return eng, [r.tokens_out for r in reqs]


@pytest.mark.parametrize("chunk", [4, pytest.param(16, marks=pytest.mark.slow)])  # 16: tier-1 wall-time budget
def test_chunked_matches_monolithic(setup, chunk):
    cfg, params = setup
    prompts = [LONG, [7, 8, 9], LONG + [5], list(range(90))]
    _, plain = run_all(cfg, params, prompts)
    eng, chunked = run_all(cfg, params, prompts, prefill_chunk=chunk)
    assert chunked == plain
    assert eng.prefill_chunks_done > 0  # the chunked path actually ran


def test_chunked_composes_with_prefix_cache(setup):
    cfg, params = setup
    prompts = [LONG + [1], LONG + [2, 3], LONG + [1, 4]]
    _, plain = run_all(cfg, params, prompts)
    eng, chunked = run_all(cfg, params, prompts, prefill_chunk=8,
                           prefix_cache_size=16)
    assert chunked == plain
    assert eng.prefix_hits >= 1  # restored prefix + chunked tail


def test_one_chunk_per_step_and_no_decode_stall(setup):
    """The fairness contract: each step advances at most one chunk, and a
    decoding row keeps emitting tokens while another slot's long prompt is
    still prefilling."""
    cfg, params = setup
    eng = ServingEngine(params, cfg, max_batch=2, max_len=96,
                        prefill_chunk=4)
    short = eng.submit([3, 4], 20)
    eng.step()  # short prompt admitted + first token
    assert len(short.tokens_out) >= 1
    long_req = eng.submit(list(range(80)), 3)
    emitted_during_prefill = 0
    while long_req.first_token_at is None:
        before_chunks = eng.prefill_chunks_done
        before_short = len(short.tokens_out)
        eng.step()
        assert eng.prefill_chunks_done - before_chunks <= 1
        if not short.done:
            emitted_during_prefill += len(short.tokens_out) - before_short
    # the 80-token prompt needed 20 chunks; the short request kept decoding
    assert emitted_during_prefill > 0
    eng.run_until_drained()
    assert long_req.done


def test_arena_edge_chunks_shrink_not_clamp(setup):
    """A chunk whose padded bucket would overflow the arena must shrink
    (dynamic_update_slice CLAMPS an out-of-bounds start, which would
    silently shift the write over earlier KV): a near-max_len prompt with a
    non-power-of-two chunk size stays bit-exact."""
    cfg, params = setup
    prompt = list(range(90))  # max_len 96, budget 1: tight fit
    eng_plain = ServingEngine(params, cfg, max_batch=1, max_len=96)
    r_plain = eng_plain.submit(prompt, 1)
    eng_plain.run_until_drained()
    for chunk in (24, 20, 7):
        eng = ServingEngine(params, cfg, max_batch=1, max_len=96,
                            prefill_chunk=chunk)
        r = eng.submit(prompt, 1)
        eng.run_until_drained()
        assert r.tokens_out == r_plain.tokens_out, chunk
        assert eng.prefill_chunks_done >= 2


class TestSpeculativeComposition:
    """Chunked prefill x speculative decoding: the two features a serving
    stack wants simultaneously (long prompts that can't stall decode AND
    accelerated decode). Chunking must stay a pure scheduling change for
    the speculative engine too."""

    @pytest.fixture(scope="class")
    def spec_setup(self, setup):
        cfg, params = setup
        dcfg = tiny_cfg(n_layers=1, d_model=32, n_heads=2, n_kv_heads=1,
                        d_ff=64)
        dparams = tm.cast_params(tm.init_params(dcfg, jax.random.PRNGKey(1)),
                                 dcfg.dtype)
        return cfg, params, dcfg, dparams

    def run_spec(self, spec_setup, prompts, budget=5, **kw):
        from hivedscheduler_tpu.models.serving import SpeculativeServingEngine

        cfg, params, dcfg, dparams = spec_setup
        eng = SpeculativeServingEngine(params, cfg, dparams, dcfg, gamma=2,
                                       max_batch=2, max_len=96, **kw)
        reqs = [eng.submit(p, budget) for p in prompts]
        eng.run_until_drained()
        return eng, [r.tokens_out for r in reqs]

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 7):
    # test_chunked_speculative_matches_plain_engine is the tier-1 cousin
    @pytest.mark.parametrize("chunk", [4, 16])
    def test_chunked_speculative_matches_unchunked(self, spec_setup, chunk):
        prompts = [LONG, [7, 8, 9], LONG + [5], list(range(80))]
        _, plain = self.run_spec(spec_setup, prompts)
        eng, chunked = self.run_spec(spec_setup, prompts,
                                     prefill_chunk=chunk)
        assert chunked == plain
        assert eng.prefill_chunks_done > 0

    def test_chunked_speculative_matches_plain_engine(self, spec_setup):
        """Chunked + speculative still equals the plain greedy engine —
        the full exactness chain (speculation is an acceleration, chunking
        is a scheduling change; together still bit-exact)."""
        cfg, params, _, _ = spec_setup
        prompts = [LONG, [3, 4], LONG + [9, 9]]
        _, plain = run_all(cfg, params, prompts)
        eng, both = self.run_spec(spec_setup, prompts, prefill_chunk=8)
        assert both == plain
        assert eng.prefill_chunks_done > 0 and eng.drafted > 0

    def test_no_spec_stall_during_chunked_prefill(self, spec_setup):
        """A speculating row keeps emitting while another slot's long
        prompt absorbs chunk-by-chunk."""
        from hivedscheduler_tpu.models.serving import SpeculativeServingEngine

        cfg, params, dcfg, dparams = spec_setup
        eng = SpeculativeServingEngine(params, cfg, dparams, dcfg, gamma=2,
                                       max_batch=2, max_len=96,
                                       prefill_chunk=4)
        short = eng.submit([3, 4], 24)
        eng.step()
        assert len(short.tokens_out) >= 1
        long_req = eng.submit(list(range(60)), 3)
        emitted_during_prefill = 0
        while long_req.first_token_at is None:
            before = len(short.tokens_out)
            eng.step()
            if not short.done:
                emitted_during_prefill += len(short.tokens_out) - before
        assert emitted_during_prefill > 0
        eng.run_until_drained()
        assert long_req.done

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_chunked_speculative_with_prefix_cache(self, spec_setup):
        prompts = [LONG + [1], LONG + [2, 3], LONG + [1, 4]]
        _, plain = self.run_spec(spec_setup, prompts)
        eng, chunked = self.run_spec(spec_setup, prompts, prefill_chunk=8,
                                     prefix_cache_size=16)
        assert chunked == plain
        assert eng.prefix_hits >= 1
