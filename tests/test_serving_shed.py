"""Per-request queue-wait deadlines + load shedding (models/serving.py).

The engine's admission is strict priority with no aging (documented
starvation caveat, PR 1); ``queue_timeout_s`` bounds it: an expired waiter
finishes with the distinct ``finish_reason="shed"`` and a per-priority-class
counter instead of waiting forever. Under sustained overload the starved
LOW-priority work is what exceeds its deadline — graceful degradation, shed
from the bottom of the priority ladder. Time is injected (``clock=``) so the
overload scenarios are deterministic on a 1-core CI box."""

import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import serving, transformer as tm  # noqa: E402
from hivedscheduler_tpu.runtime.metrics import REGISTRY  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=128, max_seq_len=128, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _shed_count(priority: str) -> float:
    return REGISTRY._counters.get(
        ("tpu_hive_serve_shed_total", (("priority", priority),)), 0.0
    )


def test_overload_sheds_low_priority_first(setup):
    """max_batch=1 under overload: the high-priority request jumps the
    queue (strict priority), so the low-priority waiter is the one whose
    deadline expires — it is shed, the high-priority one is served."""
    cfg, params = setup
    clock = FakeClock()
    eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=64,
                                queue_timeout_s=10.0, clock=clock)
    shed0_before = _shed_count("0")
    shed1_before = _shed_count("1")

    running = eng.submit([5, 9, 2], 3)          # occupies the only slot
    eng.step()
    assert eng.slots[0] is running

    low = eng.submit([1, 2], 4, priority=0)     # waits from t=0
    clock.t = 8.0
    high = eng.submit([3, 4], 4, priority=1)    # waits from t=8
    assert eng.queue[0] is high                 # strict priority: jumped ahead

    clock.t = 12.0                              # low has waited 12s > 10s,
    eng.run_until_drained()                     # high only 4s

    assert low.done and low.finish_reason == "shed"
    assert low.tokens_out == [] and low.admitted_at is None
    assert high.done and high.finish_reason == "length"
    assert len(high.tokens_out) == 4
    assert running.done and running.finish_reason == "length"
    assert _shed_count("0") == shed0_before + 1
    assert _shed_count("1") == shed1_before     # high priority never shed


def test_no_timeout_never_sheds(setup):
    cfg, params = setup
    clock = FakeClock()
    eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=64,
                                clock=clock)
    a = eng.submit([5, 9, 2], 2)
    b = eng.submit([7, 8], 2)
    clock.t = 1e9                               # ancient waiters, no deadline
    eng.run_until_drained()
    assert a.finish_reason == "length" and b.finish_reason == "length"


def test_finish_reason_eos_vs_length(setup):
    """eos wins over budget exhaustion when the stop token lands."""
    cfg, params = setup
    eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64)
    probe = eng.submit([5, 9, 2], 6)
    eng.run_until_drained()
    assert probe.finish_reason == "length"
    # replay with eos set to the first emitted token: stops immediately
    eng2 = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                 eos_id=probe.tokens_out[0])
    stopped = eng2.submit([5, 9, 2], 6)
    eng2.run_until_drained()
    assert stopped.finish_reason == "eos"
    assert stopped.tokens_out == probe.tokens_out[:1]


def test_shed_while_slots_busy_then_recycled(setup):
    """A shed request must never occupy a slot afterwards: the freed
    capacity goes to in-deadline waiters; draining terminates."""
    cfg, params = setup
    clock = FakeClock()
    eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=64,
                                queue_timeout_s=5.0, clock=clock)
    eng.submit([5, 9, 2], 2)
    stale = [eng.submit([i + 1, i + 2], 2) for i in range(3)]
    clock.t = 6.0
    fresh = eng.submit([9, 9], 2)
    eng.run_until_drained()
    assert all(r.finish_reason == "shed" for r in stale)
    assert fresh.finish_reason == "length" and len(fresh.tokens_out) == 2


def test_age_boost_bounds_low_priority_wait(setup):
    """``age_boost_secs``: an aged low-priority waiter outranks a fresh
    high-priority one once its wait buys enough effective levels — the
    bounded-wait answer to the strict-priority starvation caveat. With
    max_batch=1: the old priority-0 request (waited 25 s at 10 s/level =
    +2 levels) is admitted before the fresh priority-1 arrival."""
    cfg, params = setup
    clock = FakeClock()
    eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=64,
                                age_boost_secs=10.0, clock=clock)
    running = eng.submit([5, 9, 2], 2)
    low = eng.submit([7, 8], 2, priority=0)
    clock.t = 25.0
    high = eng.submit([9, 9], 2, priority=1)
    eng.run_until_drained()
    assert running.done and low.done and high.done
    # admission order is visible through admitted_at stamps: low (eff 0+2)
    # beat high (eff 1+0)
    assert low.admitted_at <= high.admitted_at


def test_age_boost_none_keeps_strict_priority(setup):
    """Default (None): the fresh high-priority request still jumps the
    aged low-priority waiter — exactly the pre-knob behavior."""
    cfg, params = setup
    clock = FakeClock()
    eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=64,
                                clock=clock)
    running = eng.submit([5, 9, 2], 2)
    low = eng.submit([7, 8], 2, priority=0)
    clock.t = 1000.0
    high = eng.submit([9, 9], 2, priority=1)
    eng.run_until_drained()
    assert running.done and low.done and high.done
    assert high.admitted_at <= low.admitted_at
