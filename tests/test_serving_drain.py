"""Serving graceful preemption: admission flips off (EngineDraining — the
HTTP 503 + Retry-After path), in-flight requests finish, the drain deadline
bounds the exit, and the serve CLI reaches it all via --drain-deadline +
SIGTERM (here the deterministic HIVED_FAULT_SERVE_PREEMPT_AT hook)."""

import pytest

pytest.importorskip("jax")

MODEL_KW = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq_len=64)
MODEL_ARGS = ["--d-model", "32", "--n-heads", "4", "--n-layers", "2",
              "--d-ff", "64", "--vocab-size", "64"]


def make_engine(**kw):
    import jax

    from hivedscheduler_tpu.models import serving, transformer as tm

    cfg = tm.TransformerConfig(**MODEL_KW)
    params = tm.cast_params(tm.init_params(cfg, jax.random.PRNGKey(0)),
                            cfg.dtype)
    return serving.ServingEngine(params, cfg, max_batch=2, max_len=64, **kw)


class TestEngineDrain:
    def test_begin_drain_rejects_new_finishes_in_flight(self):
        from hivedscheduler_tpu.models import serving

        eng = make_engine()
        inflight = [eng.submit([1, 2, 3], 3), eng.submit([4, 5], 4),
                    eng.submit([6, 7], 2)]  # third waits in the queue
        eng.step()
        eng.begin_drain()
        with pytest.raises(serving.EngineDraining, match="draining"):
            eng.submit([8, 9], 2)
        assert eng.drain() is True
        for r in inflight:
            # queued-but-unadmitted requests were already accepted: they
            # finish too — only NEW submissions are rejected
            assert r.done and r.finish_reason in ("eos", "length")
            assert len(r.tokens_out) > 0

    def test_drain_rejection_is_counted(self):
        from hivedscheduler_tpu.models import serving
        from hivedscheduler_tpu.runtime.metrics import REGISTRY

        eng = make_engine()
        eng.begin_drain()
        import re

        def rejected_total():
            m = re.search(
                r"^tpu_hive_serve_drain_rejected_total (\d+)",
                REGISTRY.render(), re.M)
            return int(m.group(1)) if m else 0

        n0 = rejected_total()
        with pytest.raises(serving.EngineDraining):
            eng.submit([1, 2], 2)
        assert rejected_total() == n0 + 1

    def test_drain_deadline_preempts_leftovers(self):
        # a clock that leaps 10s per reading: the first step() already
        # exceeds the 5s deadline, so the unfinished requests must be
        # finalized as preempted and the engine cleared
        t = [0.0]

        def clock():
            t[0] += 10.0
            return t[0]

        eng = make_engine(clock=clock)
        reqs = [eng.submit([1, 2, 3], 30), eng.submit([4, 5], 30),
                eng.submit([9], 30)]
        assert eng.drain(deadline_s=5.0) is False
        for r in reqs:
            assert r.done
        assert any(r.finish_reason == "preempted" for r in reqs)
        # engine is empty: nothing queued, no occupied slot
        assert not eng.queue and all(s is None for s in eng.slots)
        assert eng.step() is False

    def test_drain_without_deadline_completes_everything(self):
        eng = make_engine()
        reqs = [eng.submit([i + 1], 4) for i in range(5)]
        assert eng.drain() is True
        assert all(r.done and r.finish_reason in ("eos", "length")
                   for r in reqs)


class TestServeCliDrain:
    def test_preempt_mid_run_drains_and_reports(self, monkeypatch, capsys):
        """The full CLI path: deterministic preemption at engine step 3 —
        admitted requests finish, the pending synthetic arrivals are
        rejected through the engine's draining guard, exit stays 0."""
        from hivedscheduler_tpu import serve
        from hivedscheduler_tpu.parallel import supervisor as sup_lib

        monkeypatch.setenv(sup_lib.ENV_FAULT_SERVE_PREEMPT_AT, "3")
        rc = serve.main(MODEL_ARGS + [
            "--requests", "8", "--max-batch", "2", "--max-len", "64",
            "--max-new-tokens", "8", "--arrival-every", "2",
            "--drain-deadline", "30",
        ])
        assert rc == 0
        out, err = capsys.readouterr()
        # common.init_all logs to stderr
        assert "preemption drain" in err
        assert "rejected" in err
        # every request line printed belongs to an admitted request
        assert len([l for l in out.splitlines() if l.startswith("[")]) < 8

    def test_drain_deadline_flag_reachable(self, capsys):
        """CLAUDE.md blind spot: the new flag must be reachable (a normal
        un-preempted run with it set still completes)."""
        from hivedscheduler_tpu import serve

        rc = serve.main(MODEL_ARGS + [
            "--requests", "2", "--max-batch", "2", "--max-len", "64",
            "--max-new-tokens", "4", "--drain-deadline", "5",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert len([l for l in out.splitlines() if l.startswith("[")]) == 2
