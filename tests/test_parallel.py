"""JAX runtime tests on a virtual 8-device CPU mesh: ring/Ulysses attention
exactness vs the XLA reference, pallas flash attention, mesh topology from
scheduler slices, and the sharded train step."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.parallel import topology  # noqa: E402
from hivedscheduler_tpu.parallel.ring_attention import (  # noqa: E402
    ring_attention,
    ulysses_attention,
)
from hivedscheduler_tpu.ops.attention import flash_attention, xla_attention  # noqa: E402


def cpu_mesh(axes):
    return topology.make_mesh(axes, topology.get_devices(axes.size))


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.PRNGKey(42)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (2, 32, 4, 16)  # [B, T, H, D]
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        q = jax.random.normal(kq, shape, jnp.float32)
        k = jax.random.normal(kk, shape, jnp.float32)
        v = jax.random.normal(kv, shape, jnp.float32)
    return q, k, v


class TestRingAttention:
    def test_ring_matches_reference(self, qkv):
        q, k, v = qkv
        mesh = cpu_mesh(topology.MeshAxes(dp=2, sp=4))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref = xla_attention(q, k, v, causal=True)
        out = ring_attention(q, k, v, mesh, head_axis=None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_ring_non_causal(self, qkv):
        q, k, v = qkv
        mesh = cpu_mesh(topology.MeshAxes(sp=8))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref = xla_attention(q, k, v, causal=False)
        out = ring_attention(q, k, v, mesh, head_axis=None, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_ring_with_tp(self, qkv):
        q, k, v = qkv
        mesh = cpu_mesh(topology.MeshAxes(dp=2, tp=2, sp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref = xla_attention(q, k, v, causal=True)
        out = ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_zigzag_matches_reference(self, qkv):
        from hivedscheduler_tpu.parallel.ring_attention import zigzag_ring_attention

        q, k, v = qkv
        mesh = cpu_mesh(topology.MeshAxes(dp=2, sp=4))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref = xla_attention(q, k, v, causal=True)
        out = zigzag_ring_attention(q, k, v, mesh, head_axis=None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 15): composition
    # variant; tier-1 cousins: test_zigzag_matches_reference (the kernel)
    # and TestGQA's tp-sharded train step (the tp composition)
    def test_zigzag_with_tp(self, qkv):
        from hivedscheduler_tpu.parallel.ring_attention import zigzag_ring_attention

        q, k, v = qkv
        mesh = cpu_mesh(topology.MeshAxes(dp=2, tp=2, sp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref = xla_attention(q, k, v, causal=True)
        out = zigzag_ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_zigzag_exact_gradients(self, qkv):
        """The zigzag custom VJP (3-sub-block backward + relayout transpose)
        must produce the same dq/dk/dv as autodiff through the dense
        reference."""
        from hivedscheduler_tpu.parallel.ring_attention import zigzag_ring_attention

        q, k, v = qkv
        mesh = cpu_mesh(topology.MeshAxes(sp=8))
        cot = jax.random.normal(jax.random.PRNGKey(7), q.shape, jnp.float32)

        def loss_ref(q, k, v):
            return jnp.sum(xla_attention(q, k, v, causal=True) * cot)

        def loss_zz(q, k, v):
            return jnp.sum(zigzag_ring_attention(q, k, v, mesh, head_axis=None) * cot)

        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        zz_grads = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
        for g_ref, g_zz, name in zip(ref_grads, zz_grads, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g_zz), np.asarray(g_ref), atol=5e-5,
                err_msg=f"d{name} mismatch",
            )

    # h_kv=1 (MQA) is slow-marked: tier-1 wall-time budget (ISSUE 15) —
    # the h_kv=2 variants of both schedules are the tier-1 cousins
    # through the same compact-kv rotation path
    @pytest.mark.parametrize(
        "h_kv", [pytest.param(1, marks=pytest.mark.slow), 2])
    @pytest.mark.parametrize("impl", ["ring", "zigzag"])
    def test_gqa_compact_kv_matches_repeated_reference(self, qkv, impl, h_kv):
        """Compact-kv GQA through the ring schedules: [B,T,H_kv,D] k/v must
        produce the logits of the dense reference run on repeat-expanded
        k/v — the ring rotation ships H_kv/H of the bytes, the math is
        identical."""
        from hivedscheduler_tpu.parallel.ring_attention import (
            zigzag_ring_attention,
        )

        q, k_full, v_full = qkv
        rep = q.shape[2] // h_kv
        k = k_full[:, :, :h_kv]
        v = v_full[:, :, :h_kv]
        mesh = cpu_mesh(topology.MeshAxes(dp=2, sp=4))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref = xla_attention(
                q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2),
                causal=True,
            )
        fn = ring_attention if impl == "ring" else zigzag_ring_attention
        out = fn(q, k, v, mesh, head_axis=None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.parametrize("impl", ["ring", "zigzag"])
    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_gqa_compact_kv_exact_gradients(self, qkv, impl):
        """dq/dk/dv through the grouped-einsum backward must equal autodiff
        through the dense reference with repeat-expanded k/v (dk/dv compared
        against the reference's group-summed gradients)."""
        from hivedscheduler_tpu.parallel.ring_attention import (
            zigzag_ring_attention,
        )

        q, k_full, v_full = qkv
        h_kv, rep = 2, 2
        k = k_full[:, :, :h_kv]
        v = v_full[:, :, :h_kv]
        mesh = cpu_mesh(topology.MeshAxes(sp=8))
        cot = jax.random.normal(jax.random.PRNGKey(7), q.shape, jnp.float32)

        def loss_ref(q, k, v):
            return jnp.sum(
                xla_attention(
                    q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2),
                    causal=True,
                ) * cot
            )

        fn = ring_attention if impl == "ring" else zigzag_ring_attention

        def loss_ring(q, k, v):
            return jnp.sum(fn(q, k, v, mesh, head_axis=None) * cot)

        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        ring_grads = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        for g_ref, g_ring, name in zip(ref_grads, ring_grads, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g_ring), np.asarray(g_ref), atol=5e-5,
                err_msg=f"d{name} mismatch",
            )

    def test_zigzag_rejects_non_causal(self, qkv):
        from hivedscheduler_tpu.parallel.ring_attention import zigzag_ring_attention

        q, k, v = qkv
        mesh = cpu_mesh(topology.MeshAxes(sp=8))
        with pytest.raises(ValueError, match="causal"):
            zigzag_ring_attention(q, k, v, mesh, head_axis=None, causal=False)

    def test_ulysses_matches_reference(self, qkv):
        q, k, v = qkv
        mesh = cpu_mesh(topology.MeshAxes(dp=2, sp=4))  # H=4 divisible by sp=4
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref = xla_attention(q, k, v, causal=True)
        out = ulysses_attention(q, k, v, mesh, head_axis=None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_ulysses_compact_gqa_exact_gradients(self):
        """Gradient parity for the COMPACT transport (h_kv=2, sp=2: the k/v
        all_to_all runs on the small head axis, no expand fallback): dq/dk/
        dv must equal autodiff through the dense reference with
        repeat-expanded k/v — the same discipline the ring schedules got."""
        key = jax.random.PRNGKey(5)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            q = jax.random.normal(key, (2, 32, 4, 8), jnp.float32)
            k = jax.random.normal(jax.random.fold_in(key, 1),
                                  (2, 32, 2, 8), jnp.float32)
            v = jax.random.normal(jax.random.fold_in(key, 2),
                                  (2, 32, 2, 8), jnp.float32)
            cot = jax.random.normal(jax.random.fold_in(key, 3), q.shape)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, sp=2))

        def loss_ref(q, k, v):
            return jnp.sum(xla_attention(
                q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2),
                causal=True,
            ) * cot)

        def loss_uly(q, k, v):
            return jnp.sum(
                ulysses_attention(q, k, v, mesh, head_axis=None) * cot)

        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        uly_grads = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
        for g_ref, g_uly, name in zip(ref_grads, uly_grads, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g_uly), np.asarray(g_ref), atol=5e-5,
                err_msg=f"d{name} mismatch",
            )

    @pytest.mark.parametrize("h_kv", [2, 1])
    def test_ulysses_compact_gqa_matches_reference(self, h_kv):
        """Compact GQA k/v through the all_to_all: H_kv % sp == 0 ships the
        small head count (h_kv=2, sp=2); h_kv=1 with sp=2 can't split and
        must take the expand-locally fallback — both exact vs dense."""
        key = jax.random.PRNGKey(3)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            q = jax.random.normal(key, (2, 32, 4, 8), jnp.float32)
            k = jax.random.normal(jax.random.fold_in(key, 1),
                                  (2, 32, h_kv, 8), jnp.float32)
            v = jax.random.normal(jax.random.fold_in(key, 2),
                                  (2, 32, h_kv, 8), jnp.float32)
            ref = xla_attention(q, k, v, causal=True)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, sp=2))
        out = ulysses_attention(q, k, v, mesh, head_axis=None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


class TestFlashAttention:
    def test_flash_matches_reference(self):
        key = jax.random.PRNGKey(0)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            q, k, v = (
                jax.random.normal(kk, (1, 256, 2, 16), jnp.float32)
                for kk in jax.random.split(key, 3)
            )
            ref = xla_attention(q, k, v, causal=True)
            out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                                  interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_flash_gqa_compact_kv(self):
        # k/v carry fewer heads than q; the kernel indexes the shared head
        # directly, no materialized repeat
        key = jax.random.PRNGKey(7)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            kq, kk, kv = jax.random.split(key, 3)
            q = jax.random.normal(kq, (2, 256, 8, 16), jnp.float32)
            k = jax.random.normal(kk, (2, 256, 2, 16), jnp.float32)
            v = jax.random.normal(kv, (2, 256, 2, 16), jnp.float32)
            ref = xla_attention(q, k, v, causal=True)
            out = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("h_kv", [4, 1])
    def test_flash_gradients_match_xla(self, causal, h_kv):
        # flash_attention carries a custom_vjp (flash backward kernels);
        # grads must match the XLA reference exactly, incl. compact GQA
        key = jax.random.PRNGKey(3)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            kq, kk, kv = jax.random.split(key, 3)
            q = jax.random.normal(kq, (1, 256, 4, 16), jnp.float32)
            k = jax.random.normal(kk, (1, 256, h_kv, 16), jnp.float32)
            v = jax.random.normal(kv, (1, 256, h_kv, 16), jnp.float32)

            def loss(fn):
                return lambda q, k, v: jnp.sum(
                    jnp.sin(fn(q, k, v, causal=causal))
                )

            gf = jax.grad(
                loss(lambda q, k, v, causal: flash_attention(
                    q, k, v, causal=causal, interpret=True)),
                argnums=(0, 1, 2),
            )(q, k, v)
            gr = jax.grad(loss(xla_attention), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, ("dq", "dk", "dv")):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-5, err_msg=name
            )

    def test_train_cli_flash_attention(self):
        # CLAUDE.md blind spot: features must be reachable (and trainable)
        # from the train CLI — flash was forward-only in round 2
        from hivedscheduler_tpu import train as train_cli

        rc = train_cli.main([
            "--steps", "2", "--batch", "4", "--seq-len", "256",
            "--vocab-size", "128", "--d-model", "64", "--n-layers", "1",
            "--n-heads", "8", "--n-kv-heads", "2", "--d-ff", "128",
            "--tp", "2", "--attn", "flash", "--log-every", "1",
        ])
        assert rc == 0

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 15): heavy CLI
    # variant; tier-1 cousins: test_train_cli_flash_attention (flash CLI
    # reachability) and the pipeline step tests (tests/test_pipeline_moe
    # .py) — the no-nested-shard_map rule itself is machine-checked by
    # hivedlint SHD002
    def test_train_cli_flash_with_pipeline(self):
        # flash inside the manual pipeline context must not open a nested
        # GSPMD shard_map (CLAUDE.md shard_map rule); round-3 regression
        from hivedscheduler_tpu import train as train_cli

        rc = train_cli.main([
            "--steps", "1", "--batch", "16", "--seq-len", "256",
            "--vocab-size", "128", "--d-model", "64", "--n-layers", "2",
            "--n-heads", "8", "--d-ff", "128", "--pp", "2",
            "--microbatches", "2", "--attn", "flash", "--log-every", "1",
        ])
        assert rc == 0

    def test_xla_attention_rejects_indivisible_gqa(self):
        q = jnp.zeros((1, 8, 6, 8), jnp.float32)
        k = jnp.zeros((1, 8, 4, 8), jnp.float32)
        with pytest.raises(ValueError, match="divisible"):
            xla_attention(q, k, k)

    def test_flash_fallback_on_odd_shapes(self):
        key = jax.random.PRNGKey(1)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            q, k, v = (
                jax.random.normal(kk, (1, 30, 2, 12), jnp.float32)
                for kk in jax.random.split(key, 3)
            )
            ref = xla_attention(q, k, v, causal=True)
            out = flash_attention(q, k, v, causal=True)  # falls back
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_flash_fallback_on_cross_length_kv(self):
        """t_q != t_k (KV-cache decode shape) must fall back to the einsum
        reference even when both lengths tile: the pallas BlockSpecs size
        k/v with q's length, so the kernel would mis-read or mask wrongly."""
        key = jax.random.PRNGKey(2)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            kq, kk, kv = jax.random.split(key, 3)
            q = jax.random.normal(kq, (1, 128, 2, 16), jnp.float32)
            k = jax.random.normal(kk, (1, 256, 2, 16), jnp.float32)
            v = jax.random.normal(kv, (1, 256, 2, 16), jnp.float32)
            ref = xla_attention(q, k, v, causal=False)
            out = flash_attention(q, k, v, causal=False)  # must fall back
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestTopology:
    def test_mesh_axes(self):
        axes = topology.MeshAxes(dp=2, tp=2, sp=2)
        assert axes.size == 8
        mesh = cpu_mesh(axes)
        assert mesh.axis_names == ("dp", "fsdp", "pp", "ep", "tp", "sp")
        assert mesh.devices.shape == (2, 1, 1, 1, 2, 2)

    def test_mesh_from_slice(self):
        # a scheduler-allocated v5p 4x4x2 cell (32 chips) -> too big for tests,
        # use a 2x2x2 cell = 8 chips
        mesh = topology.mesh_from_slice(
            (2, 2, 2), topology.MeshAxes(dp=2, tp=2, sp=2),
            topology.get_devices(8),
        )
        assert mesh.size == 8
        with pytest.raises(ValueError):
            topology.mesh_from_slice((2, 2), topology.MeshAxes(dp=8),
                                     topology.get_devices(8))

    def test_infer_axes(self):
        axes = topology.infer_axes(8, tp=2, sp=2)
        assert axes.dp == 2 and axes.size == 8
        with pytest.raises(ValueError):
            topology.infer_axes(6, tp=4)

    def test_visible_chips_env(self, monkeypatch):
        from hivedscheduler_tpu.api.constants import ENV_TPU_VISIBLE_CHIPS

        monkeypatch.setenv(ENV_TPU_VISIBLE_CHIPS, "0,1,2,3")
        assert topology.visible_chip_indices() == [0, 1, 2, 3]
        monkeypatch.delenv(ENV_TPU_VISIBLE_CHIPS)
        assert topology.visible_chip_indices() is None


class TestGQA:
    def _cfg(self, **kw):
        from hivedscheduler_tpu.models import transformer as tm

        base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                    d_ff=64, max_seq_len=32, dtype=jnp.float32)
        base.update(kw)
        return tm.TransformerConfig(**base)

    @pytest.mark.parametrize("n_kv", [1, 2])
    def test_gqa_equals_mha_with_duplicated_kv(self, n_kv):
        """GQA semantics: q head i shares k/v head i // rep. Duplicating the
        kv projections rep times must reproduce the GQA logits with a plain
        MHA config exactly."""
        from hivedscheduler_tpu.models import transformer as tm

        cfg_gqa = self._cfg(n_kv_heads=n_kv)
        cfg_mha = self._cfg()
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg_gqa, jax.random.PRNGKey(0))
            assert params["layers"]["wk"].shape[2] == n_kv
            rep = 4 // n_kv
            mha_params = jax.tree.map(lambda x: x, params)
            mha_params["layers"] = dict(params["layers"])
            for w in ("wk", "wv"):
                mha_params["layers"][w] = jnp.repeat(
                    params["layers"][w], rep, axis=2
                )
            tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
            out_gqa = tm.forward(params, tokens, cfg_gqa)
            out_mha = tm.forward(mha_params, tokens, cfg_mha)
        np.testing.assert_allclose(
            np.asarray(out_gqa), np.asarray(out_mha), atol=1e-5
        )

    # ring is slow-marked: tier-1 wall-time budget (ISSUE 15) — the
    # ulysses variant is the tier-1 cousin through the same GQA x tp
    # sharded step; the ring schedule itself stays tier-1 via
    # TestRingAttention's parity tests
    @pytest.mark.parametrize(
        "impl", [pytest.param("ring", marks=pytest.mark.slow), "ulysses"])
    def test_gqa_tp_sharded_train_step(self, impl):
        import os

        if impl == "ulysses" and not os.environ.get("HIVED_ULYSSES_TRAIN_TEST"):
            # Why this one test is opt-in on the canonical 1-core dev box
            # (investigated round 5; both failure modes reproduced):
            # - in-process: passes in a fresh interpreter but SIGABRTs
            #   natively once ~35 earlier tests ran (XLA:CPU runtime state
            #   poisoning around GSPMD all_to_all + transpose under a
            #   dp x tp x sp mesh);
            # - subprocess-under-pytest: the child's 8-virtual-device
            #   collectives trip XLA's hardcoded 40 s rendezvous
            #   termination timeout ("Expected 2 threads to join ... only
            #   1 arrived") because the parent's spinning Eigen pools
            #   timeshare the single core.
            # The step itself is correct: it passes standalone (command
            # below — verified, though a COLD XLA compile on the 1-core
            # box can still trip the same 40 s rendezvous timeout; the
            # second run rides the compile cache and finishes in ~20 s),
            # and ulysses forward/grads are pinned op-level by
            # test_ulysses_compact_gqa_exact_gradients.
            pytest.skip(
                "needs a fresh interpreter + an uncontended core; run "
                "standalone: HIVED_ULYSSES_TRAIN_TEST=1 "
                "PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
                "python -m pytest 'tests/test_parallel.py::TestGQA::"
                "test_gqa_tp_sharded_train_step[ulysses]'"
            )
        self._train_step_body(impl)

    def _train_step_body(self, impl):
        from hivedscheduler_tpu.models import transformer as tm
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg = self._cfg(n_kv_heads=2, attn_impl=impl)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, tp=2, sp=2))
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64),
            token_sharding,
        )
        losses = []
        for _ in range(4):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_gqa_in_sp_pipeline_matches_dense(self, impl):
        """GQA composes with pp x sp: pipelined ring/Ulysses-attention
        logits equal the dense forward."""
        from hivedscheduler_tpu.models import transformer as tm

        cfg_pp = self._cfg(n_kv_heads=2, pipeline_microbatches=2,
                           attn_impl=impl, n_layers=4)
        cfg_ref = self._cfg(n_kv_heads=2, n_layers=4)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, pp=2, sp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg_ref, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
            ref = tm.forward(params, tokens, cfg_ref)
        out = jax.jit(lambda p, t: tm.forward(p, t, cfg_pp, mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_mqa_gspmd_ring_with_indivisible_tp_falls_back_to_repeat(self, impl):
        """Non-pipeline GSPMD ring/Ulysses with kv_heads=1 and tp=2: the
        compact-kv path cannot shard 1 head over tp=2, so the model must
        fall back to repeat-expanded k/v and still produce correct
        logits."""
        from hivedscheduler_tpu.models import transformer as tm

        cfg = self._cfg(n_kv_heads=1, attn_impl=impl)
        mesh = cpu_mesh(topology.MeshAxes(dp=2, tp=2, sp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
            ref = tm.forward(params, tokens, self._cfg(n_kv_heads=1))
        out = jax.jit(lambda p, t: tm.forward(p, t, cfg, mesh=mesh))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def test_gqa_kv_heads_not_divisible_by_tp_rejected(self):
        from hivedscheduler_tpu.models import transformer as tm

        cfg = self._cfg(n_kv_heads=1, pipeline_microbatches=2,
                        attn_impl="ring")
        mesh = cpu_mesh(topology.MeshAxes(pp=2, tp=2, sp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = tm.init_params(cfg, jax.random.PRNGKey(0))
            tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        with pytest.raises(ValueError, match="kv heads divisible by tp"):
            tm.forward(params, tokens, cfg, mesh=mesh)

    def test_invalid_kv_head_count_rejected(self):
        from hivedscheduler_tpu.models import transformer as tm

        cfg = self._cfg(n_kv_heads=3)
        with pytest.raises(AssertionError, match="not divisible"):
            tm.init_params(cfg, jax.random.PRNGKey(0))


class TestTrainStep:
    def test_sharded_train_step_decreases_loss(self):
        from hivedscheduler_tpu.models import transformer as tm
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        mesh = cpu_mesh(topology.MeshAxes(dp=2, tp=2, sp=2))
        cfg = tm.TransformerConfig(
            vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=64, attn_impl="ring",
        )
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128),
            token_sharding,
        )
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # memorizing a fixed batch

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): remat-policy
    # parity variant; tier-1 cousins: test_sharded_train_step_decreases_
    # loss + test_grad_accum_matches_full_batch (same train-step machinery
    # at the default remat)
    def test_remat_policies_match(self):
        """cfg.remat trades HBM for recompute FLOPs — it must never change
        the computed loss or gradients (f32 model: exact up to reduction
        order). Also pins the invalid-value error."""
        import dataclasses

        import pytest

        from hivedscheduler_tpu.models import transformer as tm
        from hivedscheduler_tpu.parallel.train import loss_fn

        cfg0 = tm.TransformerConfig(
            vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=64, dtype=jnp.float32,
        )
        params = tm.init_params(cfg0, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
        out = {}
        for remat in ("full", "dots", "none"):
            cfg = dataclasses.replace(cfg0, remat=remat)
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
            out[remat] = (float(loss), jax.tree.map(np.asarray, grads))
        for remat in ("dots", "none"):
            assert abs(out["full"][0] - out[remat][0]) < 1e-6
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
                out["full"][1], out[remat][1],
            )
        with pytest.raises(ValueError, match="remat"):
            loss_fn(params, tokens, dataclasses.replace(cfg0, remat="bogus"))

    def test_chunked_ce_matches_full(self):
        """ce_chunk computes the same loss AND gradients as the full
        [B,T,V] logits path (per-position CE sums linearly; f32 model)."""
        import functools

        from hivedscheduler_tpu.models import transformer as tm
        from hivedscheduler_tpu.parallel.train import loss_fn

        cfg = tm.TransformerConfig(
            vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=64, dtype=jnp.float32,
        )
        params = tm.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
        out = {}
        for chunk in (0, 8, 32):
            loss, grads = jax.value_and_grad(
                functools.partial(loss_fn, cfg=cfg, ce_chunk=chunk)
            )(params, tokens)
            out[chunk] = (float(loss), jax.tree.map(np.asarray, grads))
        for chunk in (8, 32):
            assert abs(out[0][0] - out[chunk][0]) < 1e-5, chunk
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
                out[0][1], out[chunk][1],
            )
        with pytest.raises(ValueError, match="divisible"):
            loss_fn(params, tokens, cfg, ce_chunk=7)

    def test_grad_accum_matches_full_batch(self):
        """One update with grad_accum=4 must equal the full-batch update
        (the LM loss is a mean over equal-size slices, so averaged gradients
        are exactly the full-batch gradient for a dense model)."""
        from hivedscheduler_tpu.models import transformer as tm
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        mesh = cpu_mesh(topology.MeshAxes(dp=2))
        cfg = tm.TransformerConfig(
            vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=64, dtype=jnp.float32,
        )
        tokens_host = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
        results = {}
        for accum in (1, 4):
            step, init_fn, token_sharding = make_sharded_train_step(
                cfg, mesh, grad_accum=accum
            )
            params, opt_state = init_fn(jax.random.PRNGKey(0))
            tokens = jax.device_put(tokens_host, token_sharding)
            params, opt_state, loss = step(params, opt_state, tokens)
            results[accum] = (jax.tree.map(np.asarray, params), float(loss))
        p1, l1 = results[1]
        p4, l4 = results[4]
        assert abs(l1 - l4) < 1e-5
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5), p1, p4
        )

    def test_grad_accum_indivisible_batch_rejected(self):
        from hivedscheduler_tpu.models import transformer as tm
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        mesh = cpu_mesh(topology.MeshAxes(dp=2))
        cfg = tm.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
            max_seq_len=32,
        )
        step, init_fn, token_sharding = make_sharded_train_step(
            cfg, mesh, grad_accum=3
        )
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(jnp.zeros((4, 16), jnp.int32), token_sharding)
        with pytest.raises(Exception, match="not divisible"):
            step(params, opt_state, tokens)

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_graft_entry(self):
        import __graft_entry__ as ge

        fn, args = ge.entry()
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            out = jax.jit(fn)(*args)
        assert out.shape == (2, 128, 1024)
        ge.dryrun_multichip(8)


class TestRingAttentionGradients:
    """The custom flash-style backward ring must match autodiff through the
    XLA reference attention exactly."""

    def _grads(self, fn, q, k, v):
        def loss(q, k, v):
            out = fn(q, k, v)
            # nonuniform cotangent to exercise all positions
            w = jnp.arange(out.size, dtype=jnp.float32).reshape(out.shape) / out.size
            return jnp.sum(out * w)

        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    @pytest.mark.parametrize("causal", [True, False])
    def test_ring_grads_match_reference(self, qkv, causal):
        q, k, v = qkv
        mesh = cpu_mesh(topology.MeshAxes(dp=2, sp=4))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref_grads = self._grads(
                lambda q, k, v: xla_attention(q, k, v, causal=causal), q, k, v
            )
        ring_grads = self._grads(
            lambda q, k, v: ring_attention(q, k, v, mesh, head_axis=None,
                                           causal=causal),
            q, k, v,
        )
        for a, b in zip(ring_grads, ref_grads):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, rtol=1e-4)

    def test_ring_grads_with_tp(self, qkv):
        q, k, v = qkv
        mesh = cpu_mesh(topology.MeshAxes(dp=2, tp=2, sp=2))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            ref_grads = self._grads(
                lambda q, k, v: xla_attention(q, k, v, causal=True), q, k, v
            )
        ring_grads = self._grads(
            lambda q, k, v: ring_attention(q, k, v, mesh, causal=True), q, k, v
        )
        for a, b in zip(ring_grads, ref_grads):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, rtol=1e-4)
