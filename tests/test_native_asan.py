"""Native sanitizer wiring (ISSUE 7): the C++ sources stay -Wall -Wextra
-Werror clean, and the native-vs-python parity differentials run under an
ASan/UBSan build (HIVED_NATIVE_SANITIZE=1) in a subprocess with the
sanitizer runtimes preloaded. Skips cleanly when g++ or the shared
sanitizer runtimes are absent."""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "hivedscheduler_tpu", "native")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="g++ unavailable"
)


@pytest.mark.parametrize("src", ["placement.cpp", "dataloader.cpp"])
def test_native_sources_warning_clean(src, tmp_path):
    """The strict-warnings half of the sanitize build contract: -Werror
    compiles must stay green so the ASan build (which adds these flags)
    can never fail on warnings alone."""
    proc = subprocess.run(
        ["g++", "-Wall", "-Wextra", "-Werror", "-O2", "-fPIC", "-c",
         os.path.join(NATIVE, src), "-o", str(tmp_path / "out.o")],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, f"warnings in {src}:\n{proc.stderr}"


_ASAN_DRIVER = """
import sys
sys.path.insert(0, {tests_dir!r})
import test_native as tn
from hivedscheduler_tpu import native
assert native.sanitize_mode()
assert native.available() and native.pack_available()
for num in (1, 2, 4, 8, 64):
    tn.test_differential_full_node(num)
for seed in (0, 1):
    tn.test_differential_fragmented(seed)
tn.test_packing_native_vs_python_parity(0)
print("ASAN_PARITY_OK")
"""


def test_native_parity_under_asan():
    """Build the .asan.so (address+undefined, strict warnings) and replay a
    subset of the native-vs-python parity differentials under it. Runs in a
    subprocess: ctypes dlopens into an uninstrumented CPython, so the
    sanitizer runtimes must be LD_PRELOADed before interpreter start."""
    from hivedscheduler_tpu import native

    preload = native.sanitizer_preload()
    if preload is None:
        pytest.skip("shared libasan/libubsan runtimes unavailable")
    env = dict(
        os.environ,
        HIVED_NATIVE_SANITIZE="1",
        HIVED_NATIVE="1",
        LD_PRELOAD=preload,
        # CPython leaks by design at interpreter teardown; memory ERRORS
        # (overflow/UAF/UB) still abort the run
        ASAN_OPTIONS="detect_leaks=0",
        UBSAN_OPTIONS="halt_on_error=1",
        PALLAS_AXON_POOL_IPS="",
        JAX_PLATFORMS="cpu",
    )
    driver = _ASAN_DRIVER.format(tests_dir=os.path.join(REPO, "tests"))
    proc = subprocess.run(
        [sys.executable, "-c", driver], cwd=REPO,
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert proc.returncode == 0, (
        f"ASan parity run failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "ASAN_PARITY_OK" in proc.stdout
    assert "runtime error" not in proc.stderr  # UBSan report marker
