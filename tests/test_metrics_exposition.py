"""Prometheus exposition lint: Registry.render() must stay parseable by a
strict reader — HELP/TYPE ordering, label formatting, cumulative monotone
``le`` buckets with ``+Inf`` == ``_count`` — and /metrics must serve it on
the fake-cluster webserver (ISSUE satellite; the e2e smoke in test_e2e.py
only greps for substrings)."""

import os
import re
import urllib.request

import pytest

from hivedscheduler_tpu.runtime.metrics import Registry

SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^}]*\})? (?P<value>[^ ]+)$'
)
LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse(text):
    """Strict-ish exposition parse: returns (samples, meta) where samples is
    [(name, {labels}, value)] and meta is {name: [("HELP"|"TYPE", payload)]}.
    Asserts structural rules along the way."""
    samples = []
    meta = {}
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            kind, rest = line[2:6], line[7:]
            name, _, payload = rest.partition(" ")
            meta.setdefault(name, []).append((kind, payload))
            continue
        assert not line.startswith("#"), f"unknown comment line: {line!r}"
        m = SAMPLE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        labels = dict(LABEL.findall(m.group("labels") or ""))
        value = float(m.group("value"))
        samples.append((m.group("name"), labels, value))
    return samples, meta


def series(samples, name):
    return [(l, v) for n, l, v in samples if n == name]


def base_name(sample_name):
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            return sample_name[: -len(suffix)]
    return sample_name


class TestExpositionFormat:
    def build(self):
        r = Registry()
        r.describe("tpu_hive_test_total", "a labeled counter")
        r.describe("tpu_hive_test_gauge", "a gauge")
        r.describe("tpu_hive_test_latency_seconds", "a histogram")
        r.inc("tpu_hive_test_total", routine="filter", outcome="ok")
        r.inc("tpu_hive_test_total", routine="filter", outcome="error")
        r.inc("tpu_hive_test_total", 2.5, routine="bind", outcome="ok")
        r.set_gauge("tpu_hive_test_gauge", 3)
        for v in (0.0005, 0.002, 0.02, 0.2, 2.0, 60.0):
            r.observe("tpu_hive_test_latency_seconds", v)
        for v in (0.01, 0.3):
            r.observe("tpu_hive_test_latency_seconds", v, priority="10")
        return r

    def test_help_immediately_precedes_type(self):
        text = self.build().render()
        lines = text.splitlines()
        for i, line in enumerate(lines):
            if line.startswith("# HELP "):
                name = line.split()[2]
                assert lines[i + 1].startswith(f"# TYPE {name} "), (
                    f"HELP for {name} not immediately followed by its TYPE"
                )

    def test_type_appears_once_before_samples(self):
        samples, meta = parse(self.build().render())
        for name, entries in meta.items():
            types = [p for k, p in entries if k == "TYPE"]
            assert len(types) == 1, f"{name}: TYPE emitted {len(types)} times"
        # every sample's base family carries a TYPE
        for n, _, _ in samples:
            assert base_name(n) in meta, f"sample {n} has no TYPE header"

    def test_histogram_buckets_cumulative_and_inf_equals_count(self):
        samples, _ = parse(self.build().render())
        name = "tpu_hive_test_latency_seconds"
        # split series by their non-le labels (the priority classes)
        by_series = {}
        for labels, value in series(samples, name + "_bucket"):
            key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            by_series.setdefault(key, []).append((labels["le"], value))
        counts = {tuple(sorted(l.items())): v
                  for l, v in series(samples, name + "_count")}
        assert len(by_series) == 2  # unlabeled + priority="10"
        for key, buckets in by_series.items():
            # +Inf must be last; cumulative counts monotone non-decreasing
            les = [le for le, _ in buckets]
            assert les[-1] == "+Inf"
            bounds = [float(le) for le in les[:-1]]
            assert bounds == sorted(bounds)
            values = [v for _, v in buckets]
            assert values == sorted(values), f"{key}: buckets not cumulative"
            assert values[-1] == counts[key], (
                f"{key}: +Inf bucket != _count"
            )

    def test_histogram_sum_and_labels_round_trip(self):
        samples, _ = parse(self.build().render())
        name = "tpu_hive_test_latency_seconds"
        sums = {tuple(sorted(l.items())): v
                for l, v in series(samples, name + "_sum")}
        assert sums[()] == pytest.approx(62.2225)
        assert sums[(("priority", "10"),)] == pytest.approx(0.31)
        # labeled counters render every label pair
        ctr = series(samples, "tpu_hive_test_total")
        assert ({"routine": "bind", "outcome": "ok"}, 2.5) in ctr
        assert len(ctr) == 3

    def test_default_registry_renders_clean(self):
        """The process-wide REGISTRY (whatever the suite already pushed into
        it) must always pass the same lint."""
        from hivedscheduler_tpu.runtime.metrics import REGISTRY

        samples, meta = parse(REGISTRY.render())
        for name, entries in meta.items():
            assert [p for k, p in entries if k == "TYPE"], name


class TestMetricsEndpointBoot:
    def test_fake_cluster_webserver_serves_metrics(self):
        """Boot the fake-cluster stack and lint the real /metrics payload."""
        from hivedscheduler_tpu.api.config import load_config
        from hivedscheduler_tpu.k8s.fake import FakeKubeClient
        from hivedscheduler_tpu.k8s.types import Node
        from hivedscheduler_tpu.runtime.scheduler import HivedScheduler
        from hivedscheduler_tpu.webserver import WebServer

        fixture = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "example", "config", "design", "tpu-hive.yaml",
        )
        config = load_config(fixture)
        config.web_server_address = "127.0.0.1:0"
        kube = FakeKubeClient()
        scheduler = HivedScheduler(config, kube)
        algo = scheduler.scheduler_algorithm
        for n in sorted({n for ccl in algo.full_cell_list.values()
                         for c in ccl[max(ccl)] for n in c.nodes}):
            kube.create_node(Node(name=n))
        scheduler.start()
        server = WebServer(scheduler)
        host, port = server.async_run()
        try:
            with urllib.request.urlopen(f"http://{host}:{port}/metrics") as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
        finally:
            server.stop()
        samples, meta = parse(text)
        assert ("tpu_hive_bad_nodes", {}, 0.0) in samples
        for n, _, _ in samples:
            fam = base_name(n)
            assert fam in meta and any(k == "TYPE" for k, _ in meta[fam])
