"""Capstone integration: the complete scheduler → workload handoff chain.

One flow, no shortcuts: a gang is scheduled through the real HTTP extender
protocol; the bind lands on the pod as annotations; the *workload side* then
consumes exactly those annotations — gang process topology from the
bind-info record, chip grant from the isolation annotation, a
``jax.sharding.Mesh`` over the granted chips — and runs sharded training
steps. This is the end-to-end contract a user of the framework relies on.
"""

import json
import logging
import os
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from helpers import make_pod  # noqa: E402

from hivedscheduler_tpu.api import constants as C  # noqa: E402
from hivedscheduler_tpu.api import types as api  # noqa: E402
from hivedscheduler_tpu.api.config import load_config  # noqa: E402
from hivedscheduler_tpu.common.utils import from_yaml  # noqa: E402
from hivedscheduler_tpu.k8s import serde  # noqa: E402
from hivedscheduler_tpu.k8s.fake import FakeKubeClient  # noqa: E402
from hivedscheduler_tpu.k8s.types import Node  # noqa: E402
from hivedscheduler_tpu.parallel import topology  # noqa: E402
from hivedscheduler_tpu.parallel.distributed import gang_process_info  # noqa: E402
from hivedscheduler_tpu.parallel.train import make_sharded_train_step  # noqa: E402
from hivedscheduler_tpu.models import transformer as tm  # noqa: E402
from hivedscheduler_tpu.runtime.scheduler import HivedScheduler  # noqa: E402
from hivedscheduler_tpu.webserver import WebServer  # noqa: E402

logging.getLogger().setLevel(logging.ERROR)

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)


def post(base, path, obj):
    req = urllib.request.Request(
        base + path, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_full_handoff_schedule_then_train():
    # ---- control plane: schedule a 2-pod gang over HTTP ------------------
    config = load_config(FIXTURE)
    config.web_server_address = "127.0.0.1:0"
    kube = FakeKubeClient()
    scheduler = HivedScheduler(config, kube)
    for n in sorted({n for ccl in scheduler.scheduler_algorithm.full_cell_list.values()
                     for c in ccl[max(ccl)] for n in c.nodes}):
        kube.create_node(Node(name=n))
    scheduler.start()
    server = WebServer(scheduler)
    host, port = server.async_run()
    base = f"http://{host}:{port}"
    try:
        spec = {"virtualCluster": "vc2", "priority": 10, "chipType": "v5p-chip",
                "chipNumber": 4,
                "affinityGroup": {"name": "train-job",
                                  "members": [{"podNumber": 2, "chipNumber": 4}]}}
        bound = []
        nodes = sorted(n.name for n in kube.list_nodes())
        for i in range(2):
            pod = make_pod(f"w-{i}", spec)
            kube.create_pod(pod)
            result = post(base, C.FILTER_PATH, {
                "Pod": serde.pod_to_k8s(pod), "NodeNames": nodes})
            assert result.get("NodeNames"), result
            post(base, C.BIND_PATH, {
                "PodName": pod.name, "PodNamespace": pod.namespace,
                "PodUID": pod.uid, "Node": result["NodeNames"][0]})
            bound.append(kube.get_pod("default", pod.name))
    finally:
        server.stop()

    # ---- the handoff artifacts each worker container receives ------------
    for worker in bound:
        assert worker.node_name  # bound
        assert worker.annotations[C.ANNOTATION_POD_CHIP_ISOLATION] == "0,1,2,3"
        assert C.ANNOTATION_POD_BIND_INFO in worker.annotations

    # gang placement is one contiguous 2x2x2 sub-mesh (two 4-chip hosts)
    host_origins = sorted(
        tuple(int(x) for x in w.node_name.split("/")[-1].split("-"))
        for w in bound
    )
    (a, b) = host_origins
    assert sum(abs(x - y) for x, y in zip(a, b)) == 1  # ICI-adjacent hosts

    # ---- workload side: consume the annotations exactly as train.py does --
    ranks = []
    for worker in bound:
        bind_info = api.PodBindInfo.from_dict(
            from_yaml(worker.annotations[C.ANNOTATION_POD_BIND_INFO]))
        chips = [int(x) for x in
                 worker.annotations[C.ANNOTATION_POD_CHIP_ISOLATION].split(",")]
        coord, rank, world = gang_process_info(
            bind_info, worker.node_name, my_chip_indices=chips)
        ranks.append((coord, rank, world))
    coords = {c for c, _, _ in ranks}
    assert len(coords) == 1  # all agree on the coordinator
    assert sorted(r for _, r, _ in ranks) == [0, 1]
    assert all(w == 2 for _, _, w in ranks)

    # the gang's 8 granted chips become the training mesh (CPU devices stand
    # in for the 2 hosts x 4 chips here)
    axes = topology.MeshAxes(dp=2, tp=2, sp=2)
    mesh = topology.make_mesh(axes, topology.get_devices(8))
    cfg = tm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jax.numpy.float32, attn_impl="ring",
    )
    step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
    params, opt_state = init_fn(jax.random.PRNGKey(0))
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64), token_sharding)
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
