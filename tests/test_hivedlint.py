"""hivedlint guards: the static-analysis suite runs clean on the real tree,
every rule catches its seeded-violation fixture, and the runtime lock-order
sanitizer (HIVED_LOCKCHECK=1) both catches inversions and passes a chaos
soak on the real runtime (ISSUE 7)."""

import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.hivedlint import blindspots, concurrency  # noqa: E402
from hivedscheduler_tpu.common import lockcheck  # noqa: E402


def _write(tmp_path, rel, source):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return str(path)


# ---------------------------------------------------------------------------
# the real tree is clean (tier-1, mirrors test_check_metrics)
# ---------------------------------------------------------------------------

def test_hivedlint_clean_on_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.hivedlint"], cwd=REPO,
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, (
        f"hivedlint found violations:\n{proc.stdout}{proc.stderr}"
    )
    assert "OK" in proc.stdout


def test_lock_registry_is_consistent():
    """Every hierarchy entry has a creation site and vice versa; levels are
    unique enough to define an order (distinct per name)."""
    assert set(lockcheck.LOCK_HIERARCHY) == set(lockcheck.LOCK_SITES)
    assert len(set(lockcheck.LOCK_HIERARCHY.values())) == len(
        lockcheck.LOCK_HIERARCHY)


# ---------------------------------------------------------------------------
# LCK001 / LCK002 fixtures
# ---------------------------------------------------------------------------

_HIER = {"good_lock": 10}
_SITES = {"good_lock": "pkg/owner.py"}


def test_lck001_direct_threading_lock_flagged(tmp_path):
    _write(tmp_path, "pkg/owner.py",
           "import threading\nL = threading.Lock()\n")
    got = concurrency.check_lock_registry(
        str(tmp_path / "pkg"), _HIER, _SITES, frozenset())
    assert [f.rule for f in got] == ["LCK001"]
    assert "make_lock" in got[0].message


def test_lck001_unregistered_name_and_wrong_file_flagged(tmp_path):
    _write(tmp_path, "pkg/owner.py",
           "from x import lockcheck\nA = lockcheck.make_lock('good_lock')\n"
           "B = lockcheck.make_lock('rogue_lock')\n")
    _write(tmp_path, "pkg/other.py",
           "from x import lockcheck\nC = lockcheck.make_rlock('good_lock')\n"
           "D = lockcheck.make_lock(name_var)\n")
    got = concurrency.check_lock_registry(
        str(tmp_path / "pkg"), _HIER, _SITES, frozenset())
    msgs = sorted(f.message for f in got)
    assert len(got) == 3 and all(f.rule == "LCK001" for f in got)
    assert any("'rogue_lock' is not in" in m for m in msgs)
    assert any("registers it to" in m for m in msgs)
    assert any("non-literal" in m for m in msgs)


def test_lck002_thread_spawn_outside_allowlist_flagged(tmp_path):
    _write(tmp_path, "pkg/spawner.py",
           "import threading\nt = threading.Thread(target=print)\n")
    got = concurrency.check_lock_registry(
        str(tmp_path / "pkg"), _HIER, _SITES, frozenset())
    assert [f.rule for f in got] == ["LCK002"]
    got = concurrency.check_lock_registry(
        str(tmp_path / "pkg"), _HIER, _SITES, frozenset({"pkg/spawner.py"}))
    assert got == []


# ---------------------------------------------------------------------------
# CON001: algorithm mutators
# ---------------------------------------------------------------------------

_MUTS = ["mutate", "noop"]


def test_con001_missing_assert_and_leaked_statement_flagged(tmp_path):
    path = _write(tmp_path, "hived.py", """
        class Algo:
            def mutate(self, x):
                with self.algorithm_lock:
                    self.state = x
            def noop(self):
                lockcheck.assert_serialized(self)
        """)
    got = concurrency.check_algorithm_mutators(path, _MUTS, class_name="Algo")
    assert [f.rule for f in got] == ["CON001"]
    assert "assert_serialized" in got[0].message

    path = _write(tmp_path, "hived2.py", """
        class Algo:
            def mutate(self, x):
                lockcheck.assert_serialized(self)
                self.state = x  # outside the lock!
                with self.algorithm_lock:
                    pass
            def noop(self):
                lockcheck.assert_serialized(self)
        """)
    got = concurrency.check_algorithm_mutators(path, _MUTS, class_name="Algo")
    assert len(got) == 1 and "outside the lock" in got[0].message


def test_con001_clean_shape_passes(tmp_path):
    path = _write(tmp_path, "hived.py", """
        class Algo:
            def mutate(self, x):
                '''doc'''
                lockcheck.assert_serialized(self)
                with self.algorithm_lock:
                    self.state = x
            def noop(self):
                lockcheck.assert_serialized(self)
        """)
    assert concurrency.check_algorithm_mutators(
        path, _MUTS, class_name="Algo") == []


# ---------------------------------------------------------------------------
# CON002: scheduler lock paths (direct + transitive)
# ---------------------------------------------------------------------------

def test_con002_unguarded_handler_flagged(tmp_path):
    path = _write(tmp_path, "sched.py", """
        class Sched:
            def __init__(self, kc):
                kc.on_pod_event(self._add, self._upd, self._del)
            def _add(self, pod):
                self.scheduler_algorithm.mutate(pod)   # no lock!
            def _upd(self, a, b):
                with self.scheduler_lock:
                    self.scheduler_algorithm.mutate(b)
            def _del(self, pod):
                with self.scheduler_lock:
                    self._helper(pod)
            def _helper(self, pod):
                self.scheduler_algorithm.mutate(pod)
        """)
    got = concurrency.check_scheduler_lock_paths(
        path, ["mutate"], class_name="Sched")
    assert [f.rule for f in got] == ["CON002"]
    assert "_add()" in got[0].message


def test_con002_transitive_unguarded_path_flagged(tmp_path):
    path = _write(tmp_path, "sched.py", """
        class Sched:
            def public(self, pod):
                self._helper(pod)        # enters helper with no lock
            def _locked_path(self, pod):
                with self.scheduler_lock:
                    self._helper(pod)
            def _helper(self, pod):
                self.scheduler_algorithm.mutate(pod)
        """)
    got = concurrency.check_scheduler_lock_paths(
        path, ["mutate"], class_name="Sched")
    assert len(got) == 1 and "_helper()" in got[0].message


def test_con002_thread_target_flagged_and_clean_passes(tmp_path):
    path = _write(tmp_path, "sched.py", """
        import threading
        class Sched:
            def _spawn(self):
                threading.Thread(target=self._worker).start()
            def _worker(self):
                self.scheduler_algorithm.mutate(None)
        """)
    got = concurrency.check_scheduler_lock_paths(
        path, ["mutate"], class_name="Sched")
    assert len(got) == 1 and "_worker()" in got[0].message

    path = _write(tmp_path, "clean.py", """
        class Sched:
            def public(self, pod):
                with self.scheduler_lock:
                    self._helper(pod)
            def _helper(self, pod):
                self.scheduler_algorithm.mutate(pod)
        """)
    assert concurrency.check_scheduler_lock_paths(
        path, ["mutate"], class_name="Sched") == []


def test_con003_bypass_flagged(tmp_path):
    _write(tmp_path, "pkg/webby.py", """
        def handler(s):
            s.scheduler_algorithm.mutate(None)
            s.scheduler_algorithm.get_cluster_status()  # reads are fine
        """)
    got = concurrency.check_algorithm_bypass(str(tmp_path / "pkg"), ["mutate"])
    assert [f.rule for f in got] == ["CON003"]


def test_con002_defrag_entry_points_traversed(tmp_path):
    """The CON002 fixpoint treats the defrag probe/planner entry points
    (defrag.LOCKED_ENTRY_ATTRS) as algorithm-mutating calls: reaching one
    without the scheduler lock is flagged, the locked shape passes."""
    path = _write(tmp_path, "sched.py", """
        class Sched:
            def plan_defrag_for(self, pod):
                self._planner.plan_migration(self._probe, pod, [])  # no lock!
            def resume(self):
                with self.scheduler_lock:
                    self._probe.run_probe(None, [])
        """)
    got = concurrency.check_scheduler_lock_paths(
        path, ["mutate"], class_name="Sched",
        extra_mutator_attrs={"plan_migration", "run_probe"})
    assert [f.rule for f in got] == ["CON002"]
    assert "plan_defrag_for()" in got[0].message
    # without the extension the same tree sails through — the fixture is
    # non-vacuous
    assert concurrency.check_scheduler_lock_paths(
        path, ["mutate"], class_name="Sched") == []


def test_con002_event_batch_apply_traversed(tmp_path):
    """The CON002 fixpoint treats the batched delta-apply entry points
    (eventbatch.LOCKED_APPLY_ATTRS) as algorithm-mutating calls: a path
    that drains the watch-event backlog without the scheduler lock is
    flagged, the locked shape passes — and the real registry is what the
    tree-wide check wires in."""
    from hivedscheduler_tpu.runtime import eventbatch

    assert "drain" in eventbatch.LOCKED_APPLY_ATTRS
    path = _write(tmp_path, "sched.py", """
        class Sched:
            def flush_events(self):
                self._pending.drain()          # no lock!
            def _filter_routine(self, args):
                with self.scheduler_lock:
                    self._apply_deltas_locked()
            def _apply_deltas_locked(self):
                for e in self._pending.drain():
                    pass
        """)
    got = concurrency.check_scheduler_lock_paths(
        path, ["mutate"], class_name="Sched",
        extra_mutator_attrs=set(eventbatch.LOCKED_APPLY_ATTRS))
    assert [f.rule for f in got] == ["CON002"]
    assert "flush_events()" in got[0].message
    # without the extension the same tree sails through — the fixture is
    # non-vacuous
    assert concurrency.check_scheduler_lock_paths(
        path, ["mutate"], class_name="Sched") == []


def test_dfg001_mutator_outside_probe_flagged(tmp_path):
    """DFG001: an algorithm-mutator call in any defrag module other than
    probe.py is a lock-contract bypass; the probe itself may mutate (its
    transaction rolls back)."""
    _write(tmp_path, "pkg/defrag/planner.py", """
        def sneaky(algo, pod):
            algo.delete_allocated_pod(pod)   # mutating outside the probe!
            return algo.get_affinity_group('x')  # reads are fine
        """)
    _write(tmp_path, "pkg/defrag/probe.py", """
        def sanctioned(algo, pod):
            algo.delete_allocated_pod(pod)
            algo.add_allocated_pod(pod)
        """)
    got = concurrency.check_defrag_mutator_confinement(
        str(tmp_path / "pkg"),
        ["delete_allocated_pod", "add_allocated_pod"],
        defrag_rel="pkg/defrag", probe_rel="pkg/defrag/probe.py")
    assert [f.rule for f in got] == ["DFG001"]
    assert "delete_allocated_pod" in got[0].message
    assert got[0].file.endswith("planner.py")


def test_con004_fire_under_store_lock_flagged(tmp_path):
    path = _write(tmp_path, "fake.py", """
        class Fake:
            def bad_emit(self, key):
                with self._lock:
                    self._fire(print, ())
            def good_emit(self, key):
                with self._lock:
                    ev = self._queues[key]
                self._fire(print, ())
            def _fire(self, fire, copies):
                fire(*copies)
        """)
    got = concurrency.check_store_leaf_fire(path)
    assert [f.rule for f in got] == ["CON004"]
    assert "bad_emit" in got[0].message


# ---------------------------------------------------------------------------
# CLI001 / CLI002 fixtures
# ---------------------------------------------------------------------------

def test_cli001_unreachable_and_stale_allowlist_flagged(tmp_path):
    _write(tmp_path, "cli.py", """
        def main(args):
            cfg = TransformerConfig(alpha=args.alpha, beta=args.beta)
        """)
    fields = ["alpha", "beta", "gamma", "delta"]
    got = blindspots.check_cli_reachability(
        str(tmp_path), fields,
        sites=[("cli.py", {"delta": "internal policy"})])
    assert [f.rule for f in got] == ["CLI001"]
    assert "'gamma'" in got[0].message

    got = blindspots.check_cli_reachability(
        str(tmp_path), ["alpha", "beta"],
        sites=[("cli.py", {"beta": "stale: it IS passed"})])
    assert len(got) == 1 and "stale" in got[0].message


def test_cli002_dead_flag_flagged(tmp_path):
    _write(tmp_path, "cli.py", """
        import argparse
        def main():
            p = argparse.ArgumentParser()
            p.add_argument("--used-flag", type=int)
            p.add_argument("--dead-flag", type=int)
            p.add_argument("--renamed", dest="kept", type=int)
            args = p.parse_args()
            print(args.used_flag, args.kept)
        """)
    got = blindspots.check_dead_flags(str(tmp_path), ["cli.py"])
    assert [f.rule for f in got] == ["CLI002"]
    assert "'dead_flag'" in got[0].message


# ---------------------------------------------------------------------------
# GRD001: guard drift
# ---------------------------------------------------------------------------

def test_grd001_fragment_extraction():
    frags = blindspots.regex_literal_fragments(
        r"Pod binding node mismatch: expected .* received \d+", min_len=8)
    assert frags == ["Pod binding node mismatch: expected ", " received "]
    # escapes become literals; classes/operators split
    assert blindspots.regex_literal_fragments(
        r"chain \(relaxed\) rejected", min_len=8) == [
        "chain (relaxed) rejected"]


def test_grd001_short_fragments_checked(tmp_path):
    """min_len dropped to 4 (ISSUE 8): a 4-char reworded fragment now
    fails instead of passing under the old 8-char floor."""
    _write(tmp_path, "pkg/mod.py", """
        def f(n):
            raise ValueError(f"need pow2 got {n} here")
        """)
    _write(tmp_path, "tests/test_mod.py", """
        import pytest
        def test_guard():
            with pytest.raises(ValueError, match=r"got \\d+ here"):
                pass
            with pytest.raises(ValueError, match=r"pow3"):
                pass
        """)
    got = blindspots.check_guard_drift(
        str(tmp_path / "pkg"), str(tmp_path / "tests"))
    assert [f.rule for f in got] == ["GRD001"]
    assert "'pow3'" in got[0].message


def test_grd001_pure_regex_guard_not_vacuous(tmp_path):
    """A match pattern with no literal fragment >=4 chars used to vouch
    for nothing; it must now re.search-match some package literal."""
    _write(tmp_path, "pkg/mod.py", """
        def f(n):
            raise ValueError(f"rank {n} oob")
        """)
    _write(tmp_path, "tests/test_mod.py", """
        import pytest
        def test_guard():
            with pytest.raises(ValueError, match=r"\\d+ oob"):
                pass
            with pytest.raises(ValueError, match=r"x\\d+y"):
                pass
        """)
    got = blindspots.check_guard_drift(
        str(tmp_path / "pkg"), str(tmp_path / "tests"))
    assert [f.rule for f in got] == ["GRD001"]
    assert "pure-regex" in got[0].message
    assert "matches no package" in got[0].message


def test_grd001_reworded_message_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        def f():
            raise ValueError("the gang cannot be placed on this chain")
        """)
    _write(tmp_path, "tests/test_mod.py", """
        import pytest
        def test_guard():
            with pytest.raises(ValueError,
                               match="gang cannot be placed"):
                pass
            with pytest.raises(ValueError,
                               match="some stale reworded text"):
                pass
        """)
    got = blindspots.check_guard_drift(
        str(tmp_path / "pkg"), str(tmp_path / "tests"))
    assert [f.rule for f in got] == ["GRD001"]
    assert "stale reworded" in got[0].message


# ---------------------------------------------------------------------------
# SER001: serializer drift
# ---------------------------------------------------------------------------

def test_ser001_drifted_head_and_unregistered_template_flagged(tmp_path):
    _write(tmp_path, "hivedscheduler_tpu/runtime/utils.py", """
        HEAD = '{"node":%s,"chipIsolation":[%s],"cellChain":%s}'
        """)
    _write(tmp_path, "hivedscheduler_tpu/rogue.py", """
        BLOB = '{"sneaky":%s}'
        """)
    got = blindspots.check_serializer_drift(
        str(tmp_path),
        canonical_head_keys=["node", "leafCellIsolation", "cellChain"])
    rules = sorted((f.rule, f.file) for f in got)
    assert ("SER001", "hivedscheduler_tpu/rogue.py") in rules
    assert any("drifted from the canonical serializer" in f.message
               for f in got)


def test_ser001_handrolled_loader_state_flagged(tmp_path):
    _write(tmp_path, "hivedscheduler_tpu/runtime/utils.py", """
        HEAD = '{"node":%s}'
        """)
    _write(tmp_path, "hivedscheduler_tpu/parallel/data.py", """
        class LoaderState:
            def to_dict(self):
                return {"seed": self.seed}  # hand-rolled: drift magnet
            @classmethod
            def from_dict(cls, d):
                return cls(**d)
        """)
    got = blindspots.check_serializer_drift(
        str(tmp_path), canonical_head_keys=["node"])
    msgs = [f.message for f in got]
    assert any("dataclasses.asdict" in m for m in msgs)
    assert any("dataclasses.fields" in m for m in msgs)


def test_met001_fixture_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        REGISTRY.inc('tpu_hive_orphan_total')
        """)
    got = blindspots.check_metrics_catalogue(
        REPO, package_root=str(tmp_path / "pkg"))
    assert [f.rule for f in got] == ["MET001"]
    assert "tpu_hive_orphan_total" in got[0].message


# ---------------------------------------------------------------------------
# OBS001: journal event-type / wait-bucket schema registry (ISSUE 11)
# ---------------------------------------------------------------------------

_OBS_SCHEMA = {"bind": "doc", "queued": "doc", "never_emitted": "doc"}
_OBS_BUCKETS = {"vc_quota": "doc"}


def test_obs001_unregistered_event_type_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        journal.emit("rogue_event", "g")
        obs_journal.note_phase("g", "running", "bind")
        journal.note_wait("g", "vc_quota")
        """)
    got = blindspots.check_journal_schema(
        REPO, package_root=str(tmp_path / "pkg"),
        schema=dict(_OBS_SCHEMA), buckets=dict(_OBS_BUCKETS))
    assert [f.rule for f in got] == ["OBS001", "OBS001"]
    msgs = sorted(f.message for f in got)
    assert any("'rogue_event'" in m and "not registered" in m for m in msgs)
    # vice-versa: the registered-but-never-emitted row is flagged too
    assert any("'never_emitted'" in m and "never emitted" in m
               for m in msgs)


def test_obs001_unregistered_bucket_and_dynamic_type_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        journal.note_wait("g", "rogue_bucket", etype="queued")
        name = "bind"
        journal.emit(name, "g")
        obs_journal.emit("bind", "g")
        """)
    got = blindspots.check_journal_schema(
        REPO, package_root=str(tmp_path / "pkg"),
        schema={"bind": "d", "queued": "d"},
        buckets=dict(_OBS_BUCKETS))
    msgs = sorted(f.message for f in got)
    assert len(got) == 2 and all(f.rule == "OBS001" for f in got)
    assert any("'rogue_bucket'" in m for m in msgs)
    assert any("non-literal" in m for m in msgs)


def test_obs001_clean_fixture_passes(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        journal.emit("bind", "g")
        journal.note_wait("g", "vc_quota")
        """)
    got = blindspots.check_journal_schema(
        REPO, package_root=str(tmp_path / "pkg"),
        schema={"bind": "d", "queued": "d"},
        buckets=dict(_OBS_BUCKETS))
    assert got == []


def test_obs001_real_tree_schema_is_exact():
    """Clean on the real package (also covered by the tier-1 full-suite
    run, but pinned here so a schema drift names the rule directly)."""
    got = blindspots.check_journal_schema(REPO)
    assert got == []


# the request-leg extension (ISSUE 13): seeded fixtures prove each
# direction is non-vacuous
_LEG_SCHEMA = {"request_submit": "d", "request_leg": "d",
               "request_done": "d"}
_LEG_REGISTRY = {"route": "d", "never_emitted_leg": "d"}


def test_obs001_unregistered_leg_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        journal.note_request_submit("fleet/0")
        journal.note_leg("fleet/0", "rogue_leg")
        obs_journal.note_leg("fleet/0", "route")
        journal.note_request_done("fleet/0", "length")
        """)
    got = blindspots.check_journal_schema(
        REPO, package_root=str(tmp_path / "pkg"),
        schema=dict(_LEG_SCHEMA), buckets={"vc_quota": "d"},
        legs=dict(_LEG_REGISTRY))
    msgs = sorted(f.message for f in got)
    assert all(f.rule == "OBS001" for f in got)
    assert any("'rogue_leg'" in m and "not registered" in m for m in msgs)
    # vice versa: the registered-but-never-emitted leg is flagged too
    assert any("'never_emitted_leg'" in m and "never emitted" in m
               for m in msgs)
    assert len(got) == 2


def test_obs001_non_literal_leg_and_unregistered_implied_event(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        leg = "route"
        journal.note_leg("fleet/0", leg)
        journal.note_request_done("fleet/0", "length")
        """)
    # note_request_done implies request_done, which this schema lacks
    got = blindspots.check_journal_schema(
        REPO, package_root=str(tmp_path / "pkg"),
        schema={"request_leg": "d"}, buckets={"vc_quota": "d"},
        legs={"route": "d"})
    msgs = sorted(f.message for f in got)
    assert any("non-literal leg" in m for m in msgs)
    assert any("'request_done'" in m and "not registered" in m
               for m in msgs)


def test_obs001_clean_leg_fixture_passes(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        journal.note_request_submit("fleet/0")
        journal.note_leg("fleet/0", "route")
        journal.note_request_done("fleet/0", "length")
        """)
    got = blindspots.check_journal_schema(
        REPO, package_root=str(tmp_path / "pkg"),
        schema=dict(_LEG_SCHEMA), buckets={"vc_quota": "d"},
        legs={"route": "d"})
    assert got == []


# ---------------------------------------------------------------------------
# OBS002: capacity-ledger chip-state registry (ISSUE 14) — seeded
# fixtures prove both directions are non-vacuous
# ---------------------------------------------------------------------------

_LEDGER_STATES = {"busy_guaranteed": "d", "idle_free": "d",
                  "never_produced_state": "d"}


def test_obs002_unregistered_state_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        ledger.transition("n0", [0], "rogue_state")
        obs_ledger.LEDGER.transition("n0", [1], "busy_guaranteed")
        ledger.set_idle_diagnosis("idle_free")
        """)
    got = blindspots.check_ledger_states(
        REPO, package_root=str(tmp_path / "pkg"),
        states=dict(_LEDGER_STATES))
    msgs = sorted(f.message for f in got)
    assert all(f.rule == "OBS002" for f in got)
    assert any("'rogue_state'" in m and "not registered" in m
               for m in msgs)
    # vice versa: the registered-but-never-produced row is flagged too
    assert any("'never_produced_state'" in m and "never produced" in m
               for m in msgs)
    assert len(got) == 2


def test_obs002_non_literal_state_is_legal_mapping_path(tmp_path):
    # the busy_state()/IDLE_STATE_FOR_BUCKET mapping paths pass
    # variables — the runtime validates those, the lint does not flag
    _write(tmp_path, "pkg/mod.py", """
        state = pick()
        ledger.transition("n0", [0], state)
        obs_ledger.LEDGER.hint_flavor("g", "busy_guaranteed")
        lg.register_node("n0", 4, state="idle_free")
        """)
    got = blindspots.check_ledger_states(
        REPO, package_root=str(tmp_path / "pkg"),
        states={"busy_guaranteed": "d", "idle_free": "d"})
    assert got == []


def test_obs002_registry_keys_do_not_vouch_for_themselves(tmp_path):
    # a fixture obs/ledger.py whose CHIP_STATES dict names a state no
    # call site produces: the dict's own literals must not count
    _write(tmp_path, "pkg/obs/ledger.py", """
        CHIP_STATES = {"busy_guaranteed": "doc", "orphan_row": "doc"}
        def busy_state():
            return "busy_guaranteed"
        """)
    got = blindspots.check_ledger_states(
        REPO, package_root=str(tmp_path / "pkg"),
        states={"busy_guaranteed": "d", "orphan_row": "d"})
    assert [f.rule for f in got] == ["OBS002"]
    assert "'orphan_row'" in got[0].message


def test_obs002_real_tree_registry_is_exact():
    got = blindspots.check_ledger_states(REPO)
    assert got == []


# ---------------------------------------------------------------------------
# OBS003: workload goodput step-phase registry (ISSUE 16) — seeded
# fixtures prove both directions are non-vacuous
# ---------------------------------------------------------------------------

_GOODPUT_PHASES = {"step_compute": "d", "data_wait": "d",
                   "never_produced_phase": "d"}


def test_obs003_unregistered_phase_flagged(tmp_path):
    _write(tmp_path, "pkg/mod.py", """
        obs_goodput.phase("rogue_phase")
        goodput.GOODPUT.phase("step_compute")
        with goodput.span("data_wait"):
            pass
        """)
    got = blindspots.check_goodput_phases(
        REPO, package_root=str(tmp_path / "pkg"),
        phases=dict(_GOODPUT_PHASES))
    msgs = sorted(f.message for f in got)
    assert all(f.rule == "OBS003" for f in got)
    assert any("'rogue_phase'" in m and "not registered" in m
               for m in msgs)
    # vice versa: the registered-but-never-produced row is flagged too
    assert any("'never_produced_phase'" in m and "never produced" in m
               for m in msgs)
    assert len(got) == 2


def test_obs003_non_literal_phase_is_legal(tmp_path):
    # computed phases (the note_step classification passes variables
    # through self.phase) are validated by the runtime, not the lint;
    # start() with the phase defaulted is legal too
    _write(tmp_path, "pkg/mod.py", """
        ph = classify()
        goodput.phase(ph)
        obs_goodput.GOODPUT.start()
        gp.span(phase="data_wait")
        _goodput.phase("step_compute")
        """)
    got = blindspots.check_goodput_phases(
        REPO, package_root=str(tmp_path / "pkg"),
        phases={"step_compute": "d", "data_wait": "d"})
    assert got == []


def test_obs003_registry_keys_do_not_vouch_for_themselves(tmp_path):
    # a fixture obs/goodput.py whose STEP_PHASES dict names a phase no
    # call site produces: the dict's own literals must not count
    _write(tmp_path, "pkg/obs/goodput.py", """
        STEP_PHASES = {"step_compute": "doc", "orphan_row": "doc"}
        def classify():
            return "step_compute"
        """)
    got = blindspots.check_goodput_phases(
        REPO, package_root=str(tmp_path / "pkg"),
        phases={"step_compute": "d", "orphan_row": "d"})
    assert [f.rule for f in got] == ["OBS003"]
    assert "'orphan_row'" in got[0].message


def test_obs003_real_tree_registry_is_exact():
    got = blindspots.check_goodput_phases(REPO)
    assert got == []


# ---------------------------------------------------------------------------
# HIVED_LOCKCHECK runtime sanitizer
# ---------------------------------------------------------------------------

def test_lockcheck_disabled_returns_plain_lock(monkeypatch):
    monkeypatch.delenv("HIVED_LOCKCHECK", raising=False)
    lk = lockcheck.make_lock("metrics_lock")
    assert not isinstance(lk, lockcheck.CheckedLock)


def test_lockcheck_order_violation_raises(monkeypatch):
    monkeypatch.setenv("HIVED_LOCKCHECK", "1")
    sched = lockcheck.make_rlock("scheduler_lock")
    store = lockcheck.make_rlock("store_lock")
    with sched:
        with store:  # 10 -> 50: fine
            pass
    with pytest.raises(lockcheck.LockOrderError, match="lock-order violation"):
        with store:
            with sched:
                pass


def test_lockcheck_reentrant_and_timeout_acquire(monkeypatch):
    monkeypatch.setenv("HIVED_LOCKCHECK", "1")
    sched = lockcheck.make_rlock("scheduler_lock")
    with sched:
        with sched:  # reentrant: no order check against itself
            assert sched._is_owned()
        assert sched.acquire(timeout=0.1)
        sched.release()
    assert not sched._is_owned()
    with pytest.raises(lockcheck.LockOrderError, match="does not hold"):
        sched.release()


def test_lockcheck_unregistered_name_rejected(monkeypatch):
    monkeypatch.setenv("HIVED_LOCKCHECK", "1")
    with pytest.raises(lockcheck.LockOrderError, match="not in LOCK_HIERARCHY"):
        lockcheck.make_lock("never_registered_lock")


def test_lockcheck_contended_acquire_failure_not_recorded(monkeypatch):
    monkeypatch.setenv("HIVED_LOCKCHECK", "1")
    lk = lockcheck.make_lock("metrics_lock")
    hold = threading.Event()
    done = threading.Event()

    def holder():
        with lk:
            hold.set()
            done.wait(5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert hold.wait(5)
    assert lk.acquire(timeout=0.05) is False
    assert not lk._is_owned()  # failed acquire must not leak into the stack
    done.set()
    t.join(5)


def test_lockcheck_assert_serialized_contract(monkeypatch):
    monkeypatch.setenv("HIVED_LOCKCHECK", "1")
    sched = lockcheck.make_rlock("scheduler_lock")

    class Algo:
        pass

    algo = Algo()
    lockcheck.assert_serialized(algo)  # unowned: standalone use is fine
    lockcheck.serialize_under(algo, "scheduler_lock")
    with pytest.raises(lockcheck.LockOrderError, match="single-threaded"):
        lockcheck.assert_serialized(algo)
    with sched:
        lockcheck.assert_serialized(algo)


def test_lockcheck_late_enable_switchable(monkeypatch):
    """ISSUE 8 satellite (PR 7's "NOT done" gap): a late=True singleton
    lock honors HIVED_LOCKCHECK enabled AFTER creation."""
    monkeypatch.delenv("HIVED_LOCKCHECK", raising=False)
    lk = lockcheck.make_lock("metrics_lock", late=True)
    assert isinstance(lk, lockcheck.SwitchableLock)
    with lk:
        pass  # plain path while disabled
    monkeypatch.setenv("HIVED_LOCKCHECK", "1")
    sched = lockcheck.make_rlock("scheduler_lock")
    with pytest.raises(lockcheck.LockOrderError, match="lock-order violation"):
        with lk:        # leaf level 80
            with sched:  # level 10 under 80: inversion
                pass
    with sched:
        with lk:  # legal order still fine
            pass


def test_lockcheck_late_enable_covers_import_time_singletons(monkeypatch):
    """The REAL metrics REGISTRY singleton — imported long before the env
    var is set — still comes under the sanitizer."""
    monkeypatch.delenv("HIVED_LOCKCHECK", raising=False)
    from hivedscheduler_tpu.runtime.metrics import REGISTRY

    assert isinstance(REGISTRY._lock, lockcheck.SwitchableLock)
    monkeypatch.setenv("HIVED_LOCKCHECK", "1")
    sched = lockcheck.make_rlock("scheduler_lock")
    with pytest.raises(lockcheck.LockOrderError, match="lock-order violation"):
        with REGISTRY._lock:
            with sched:
                pass
    with sched:  # the routine scheduler(10) -> metrics(80) chain
        with REGISTRY._lock:
            pass


def test_lockcheck_late_flip_mid_hold(monkeypatch):
    """Enabling the sanitizer while a switchable lock is held must pair
    the release with its (plain) acquire instead of raising."""
    monkeypatch.delenv("HIVED_LOCKCHECK", raising=False)
    lk = lockcheck.make_lock("trace_lock", late=True)
    assert lk.acquire()
    monkeypatch.setenv("HIVED_LOCKCHECK", "1")
    lk.release()  # paired with the plain-path acquire
    with lk:  # checked from here on
        assert lk._is_owned()
    assert not lk.locked()


def test_lockcheck_chaos_soak_smoke(monkeypatch):
    """The wired-in detector: a short chaos soak on the real runtime under
    HIVED_LOCKCHECK=1. Lock-order and scheduler-lock-held assertions are
    live on every schedule/bind/flap/restart; any inversion raises instead
    of deadlocking. (The full soak ladder runs in test_chaos.py; every soak
    becomes a race detector when the env var is set.)"""
    monkeypatch.setenv("HIVED_LOCKCHECK", "1")
    from hivedscheduler_tpu.chaos.harness import ChaosHarness

    h = ChaosHarness(seed=3)
    assert isinstance(h.scheduler.scheduler_lock, lockcheck.CheckedLock)
    assert isinstance(h.algo.algorithm_lock, lockcheck.CheckedLock)
    assert h.algo._lockcheck_serialized_by == "scheduler_lock"
    report = h.run(6)
    assert report["violations"] == []
    assert report["schedules"] == 6
