"""LoRA adapters (models/transformer.py lora_* leaves +
parallel/train.make_sharded_lora_train_step).

Invariants: zero-init B means the adapted model IS the base model; merging
folds the adapters away exactly; the LoRA train step moves only adapters;
tp-sharded LoRA forward equals single-device."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import transformer as tm  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq_len=32, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


def _perturb_lora_b(params, seed=5):
    """Random-fill the B factors so the adapters actually do something
    (covers whatever adapters the tree carries, incl. lora_mlp ones)."""
    layers = dict(params["layers"])
    k = jax.random.PRNGKey(seed)
    for name in sorted(layers):
        if not (name.startswith("lora_") and name.endswith("_b")):
            continue
        k, sub = jax.random.split(k)
        b = layers[name]
        layers[name] = 0.1 * jax.random.normal(sub, b.shape, b.dtype)
    return {**params, "layers": layers}


class TestLoRA:
    def test_zero_init_matches_base_model(self):
        cfg = cfg_of(lora_rank=4)
        base_cfg = cfg_of()
        params = tm.init_params(cfg, jax.random.PRNGKey(0))
        base_params, _ = tm.split_lora_params(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        np.testing.assert_allclose(
            np.asarray(tm.forward(params, tokens, cfg)),
            np.asarray(tm.forward(base_params, tokens, base_cfg)),
            atol=1e-6,
        )

    def test_merge_matches_adapter_forward(self):
        cfg = cfg_of(lora_rank=4, lora_alpha=8.0, n_kv_heads=2)
        params = _perturb_lora_b(tm.init_params(cfg, jax.random.PRNGKey(0)))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        adapted = tm.forward(params, tokens, cfg)
        merged = tm.merge_lora(params, cfg)
        assert not any(k.startswith("lora_") for k in merged["layers"])
        base_cfg = cfg_of(n_kv_heads=2)
        np.testing.assert_allclose(
            np.asarray(tm.forward(merged, tokens, base_cfg)),
            np.asarray(adapted), atol=1e-5,
        )
        # the adapters must actually change the function, else this test
        # proves nothing
        base_params, _ = tm.split_lora_params(params)
        base_out = tm.forward(base_params, tokens, base_cfg)
        assert np.abs(np.asarray(adapted) - np.asarray(base_out)).max() > 1e-4

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_mlp_adapters_identity_merge_and_training(self):
        """lora_mlp=True: zero-init is exactly the base model; merge folds
        gate/up/down deltas exactly; a tp-sharded LoRA step trains the MLP
        adapters too; MoE configs are rejected."""
        from hivedscheduler_tpu.parallel import topology
        from hivedscheduler_tpu.parallel.train import make_sharded_lora_train_step

        cfg = cfg_of(lora_rank=3, lora_alpha=6.0, lora_mlp=True)
        base_cfg = cfg_of()
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
        params = tm.init_params(cfg, jax.random.PRNGKey(0))
        assert "lora_w_down_a" in params["layers"]
        base_params, _ = tm.split_lora_params(params)
        np.testing.assert_allclose(
            np.asarray(tm.forward(params, tokens, cfg)),
            np.asarray(tm.forward(base_params, tokens, base_cfg)), atol=1e-6,
        )
        params = _perturb_lora_b(params)
        adapted = tm.forward(params, tokens, cfg)
        merged = tm.merge_lora(params, cfg)
        assert not any(k.startswith("lora_") for k in merged["layers"])
        np.testing.assert_allclose(
            np.asarray(tm.forward(merged, tokens, base_cfg)),
            np.asarray(adapted), atol=1e-5,
        )
        # ... and the MLP deltas actually matter: zero them, outputs change
        zeroed = {**params, "layers": {
            k: (jnp.zeros_like(v) if k.startswith("lora_w_") and k.endswith("_b")
                else v)
            for k, v in params["layers"].items()}}
        assert np.abs(np.asarray(tm.forward(zeroed, tokens, cfg))
                      - np.asarray(adapted)).max() > 1e-5

        mesh = topology.make_mesh(topology.MeshAxes(tp=2), topology.get_devices(2))
        step_fn, init_fn, _tok = make_sharded_lora_train_step(cfg, mesh)
        base, lora, opt = init_fn(jax.random.PRNGKey(0))
        gate_a_before = np.asarray(lora["layers"]["lora_w_gate_a"])  # donated
        lora2, opt, loss = step_fn(base, lora, opt, tokens)
        assert np.isfinite(float(loss))
        moved = float(np.abs(
            np.asarray(lora2["layers"]["lora_w_gate_a"]) - gate_a_before
        ).sum())
        assert moved > 0.0

        with pytest.raises(ValueError, match="dense"):
            tm.init_params(cfg_of(lora_rank=2, lora_mlp=True, n_experts=2),
                           jax.random.PRNGKey(0))

    def test_lora_step_trains_only_adapters(self):
        from hivedscheduler_tpu.parallel import topology
        from hivedscheduler_tpu.parallel.train import make_sharded_lora_train_step

        cfg = cfg_of(lora_rank=2)
        mesh = topology.make_mesh(topology.MeshAxes(dp=2), topology.get_devices(2))
        step_fn, init_fn, token_sharding = make_sharded_lora_train_step(cfg, mesh)
        base, lora, opt_state = init_fn(jax.random.PRNGKey(0))
        base_before = jax.tree.map(lambda x: np.asarray(x).copy(), base)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64),
            token_sharding,
        )
        losses = []
        for _ in range(5):
            lora, opt_state, loss = step_fn(base, lora, opt_state, tokens)
            losses.append(float(loss))
        # base unchanged bitwise; adapters moved; loss decreased on the
        # fixed batch
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
            base, base_before,
        )
        moved = jax.tree.reduce(
            lambda acc, x: acc + float(jnp.abs(x).sum()), lora["layers"], 0.0
        )
        assert moved > 0.0
        assert losses[-1] < losses[0]

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_lora_grad_accum_matches_full_batch(self):
        """One LoRA update with grad_accum=4 must equal the full-batch
        update exactly (same argument as the dense train step: the LM loss
        is a mean over equal slices; adapter grads average linearly)."""
        from hivedscheduler_tpu.parallel import topology
        from hivedscheduler_tpu.parallel.train import make_sharded_lora_train_step

        cfg = cfg_of(lora_rank=2)
        mesh = topology.make_mesh(topology.MeshAxes(dp=2), topology.get_devices(2))
        tokens_host = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
        results = {}
        for accum in (1, 4):
            step_fn, init_fn, token_sharding = make_sharded_lora_train_step(
                cfg, mesh, grad_accum=accum
            )
            base, lora, opt_state = init_fn(jax.random.PRNGKey(0))
            lora = _perturb_lora_b(lora)  # make the adapters active
            tokens = jax.device_put(tokens_host, token_sharding)
            lora, opt_state, loss = step_fn(base, lora, opt_state, tokens)
            results[accum] = (jax.tree.map(np.asarray, lora), float(loss))
        l1, loss1 = results[1]
        l4, loss4 = results[4]
        assert abs(loss1 - loss4) < 1e-5
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5), l1, l4
        )

    def test_tp_sharded_lora_matches_single_device(self):
        from hivedscheduler_tpu.parallel import topology

        cfg = cfg_of(lora_rank=4, n_kv_heads=2)
        params = _perturb_lora_b(tm.init_params(cfg, jax.random.PRNGKey(0)))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
        want = tm.forward(params, tokens, cfg)
        mesh = topology.make_mesh(
            topology.MeshAxes(dp=2, tp=2), topology.get_devices(4)
        )
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        shardings = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec), tm.sharding_specs(cfg),
            is_leaf=lambda x: isinstance(x, P),
        )
        sp = jax.device_put(params, shardings)
        st = jax.device_put(tokens, NamedSharding(mesh, tm.activation_spec()))
        got = jax.jit(lambda p, t: tm.forward(p, t, cfg))(sp, st)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_lora_inside_pipeline_matches_nonpipelined(self):
        """LoRA adapters inside GPipe stages: one adapter update on a
        dp x pp mesh equals the non-pipelined update exactly (the stage
        body's manual-mode adapter einsums and the wo-adapter's shared
        row-parallel psum were already correct; this pins it)."""
        from hivedscheduler_tpu.parallel import topology
        from hivedscheduler_tpu.parallel.train import make_sharded_lora_train_step

        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
        for mlp in (False, True):
            out = {}
            for tag, kw, axes in (
                # pp x tp + lora_mlp pins the manual-mode psum sharing of
                # the wo/down adapter einsums inside the stage body
                ("pp", dict(pipeline_microbatches=2),
                 topology.MeshAxes(pp=2, tp=2)),
                ("ref", {}, topology.MeshAxes(dp=2)),
            ):
                cfg = cfg_of(lora_rank=2, lora_mlp=mlp, **kw)
                mesh = topology.make_mesh(axes, topology.get_devices(axes.size))
                step, init_fn, tok_sh = make_sharded_lora_train_step(cfg, mesh)
                base, lora, opt = init_fn(jax.random.PRNGKey(0))
                lora2, opt, loss = step(base, lora, opt,
                                        jax.device_put(tokens, tok_sh))
                out[tag] = (float(loss), jax.tree.map(np.asarray, lora2))
            assert abs(out["pp"][0] - out["ref"][0]) < 1e-5, mlp
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
                out["pp"][1], out["ref"][1],
            )

    def test_split_combine_roundtrip(self):
        cfg = cfg_of(lora_rank=2)
        params = tm.init_params(cfg, jax.random.PRNGKey(0))
        base, lora = tm.split_lora_params(params)
        assert not any(k.startswith("lora_") for k in base["layers"])
        assert set(lora["layers"]) == {
            f"lora_{n}_{ab}" for n in ("wq", "wk", "wv", "wo") for ab in "ab"
        }
        back = tm.combine_lora_params(base, lora)
        assert jax.tree.structure(back) == jax.tree.structure(params)

    def test_merged_params_decode(self):
        """Merged LoRA params feed the serving path unchanged."""
        from hivedscheduler_tpu.models import decode

        cfg = cfg_of(lora_rank=2)
        params = _perturb_lora_b(tm.init_params(cfg, jax.random.PRNGKey(0)))
        merged = tm.merge_lora(params, cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 5), 0, 64)
        out = decode.generate(merged, prompt, cfg_of(), 4)
        assert out.shape == (1, 4)
