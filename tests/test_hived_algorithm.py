"""HivedAlgorithm behavioral tests.

Ports the reference's test strategy (``pkg/algorithm/hived_algorithm_test.go``,
SURVEY.md §4): a fake multi-node cluster defined purely by config YAML, driven
through the algorithm layer with pod specs, suggested-node lists, and node
health events — no real K8s anywhere. Covers: normal operations with
placement goldens, gang scheduling, user-error panics (HTTP 4xx class),
stateful preemption chains, lazy preemption, bad nodes with doomed-bad-cell
binding, safe-relaxed buddy allocation, reconfiguration replay, and invalid
initial VC assignments.
"""

import logging
import random

import pytest

from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.algorithm.constants import (
    CELL_FREE,
    CELL_RESERVED,
    CELL_RESERVING,
    CELL_USED,
    GROUP_ALLOCATED,
    GROUP_BEING_PREEMPTED,
    GROUP_PREEMPTING,
)
from hivedscheduler_tpu.common.utils import to_yaml
from hivedscheduler_tpu.k8s.types import Container, Node, Pod
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE, PREEMPTING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

logging.getLogger().setLevel(logging.ERROR)

import os

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)


from helpers import all_node_names, make_pod, set_healthy_nodes


@pytest.fixture
def algo():
    random.seed(0)
    h = HivedAlgorithm(load_config(FIXTURE))
    set_healthy_nodes(h)
    return h


def schedule_and_allocate(h, pod, suggested=None, phase=FILTERING_PHASE):
    sn = suggested if suggested is not None else all_node_names(h)
    r = h.schedule(pod, sn, phase)
    assert r.pod_bind_info is not None, f"expected bind, got {r.pod_wait_info or r.pod_preempt_info}"
    bp = new_binding_pod(pod, r.pod_bind_info)
    h.add_allocated_pod(bp)
    return bp, r.pod_bind_info


# ---------------------------------------------------------------------------
# normal operations
# ---------------------------------------------------------------------------


class TestNormalOperations:
    def test_single_chip_pod(self, algo):
        pod = make_pod("p1", {"virtualCluster": "vc2", "priority": 0,
                              "chipType": "v5e-chip", "chipNumber": 1})
        bp, info = schedule_and_allocate(algo, pod)
        assert info.node == "v5e-host0/0-0"
        assert len(info.leaf_cell_isolation) == 1
        assert info.cell_chain == "v5e-8"
        # isolation annotation is the TPU_VISIBLE_CHIPS handoff
        assert bp.annotations[C.ANNOTATION_POD_CHIP_ISOLATION] == str(
            info.leaf_cell_isolation[0]
        )

    def test_full_host_gang(self, algo):
        pod = make_pod("p8", {"virtualCluster": "vc2", "priority": 0,
                              "chipType": "v5e-chip", "chipNumber": 8})
        _, info = schedule_and_allocate(algo, pod)
        assert sorted(info.leaf_cell_isolation) == list(range(8))

    def test_multi_host_gang_is_contiguous_submesh(self, algo):
        spec = {"virtualCluster": "vc1", "priority": 5, "chipType": "v5p-chip",
                "chipNumber": 4,
                "affinityGroup": {"name": "g32",
                                  "members": [{"podNumber": 8, "chipNumber": 4}]}}
        origins = []
        for i in range(8):
            _, info = schedule_and_allocate(algo, make_pod(f"g32-{i}", spec))
            origins.append(tuple(int(x) for x in info.node.split("/")[-1].split("-")))
        # the 8 hosts must tile one contiguous 4x4x2 sub-mesh (VC1's cell type)
        xs = sorted({o[0] for o in origins})
        ys = sorted({o[1] for o in origins})
        zs = sorted({o[2] for o in origins})
        assert xs == [0, 2] and ys == [0, 2]
        assert zs in ([0, 1], [2, 3])
        assert len(set(origins)) == 8

    def test_gang_prefers_contiguous_submesh_over_fragments(self, algo):
        """Gang-level LCA minimization: with one 2x2x2 partially used, an
        8-chip gang must take a WHOLE free 2x2x2 (contiguous ICI sub-mesh),
        not an L-shape straddling the fragmented cell and a fresh one."""
        frag = {"virtualCluster": "vc2", "priority": 5, "chipType": "v5p-chip",
                "chipNumber": 1}
        schedule_and_allocate(algo, make_pod("frag", frag))
        gang = {"virtualCluster": "vc2", "priority": 5, "chipType": "v5p-chip",
                "chipNumber": 4,
                "affinityGroup": {"name": "contig",
                                  "members": [{"podNumber": 2, "chipNumber": 4}]}}
        origins = []
        for i in range(2):
            _, info = schedule_and_allocate(algo, make_pod(f"contig-{i}", gang))
            origins.append(tuple(int(x) for x in info.node.split("/")[-1].split("-")))
        # the two hosts must be the two halves of one 2x2x2: same (x, y),
        # z in {0, 1}, and 2x2x2-aligned
        (x0, y0, z0), (x1, y1, z1) = sorted(origins)
        assert (x0, y0) == (x1, y1) and [z0, z1] == [0, 1], origins
        assert x0 % 2 == 0 and y0 % 2 == 0, origins

    def test_pinned_cell_scheduling(self, algo):
        spec = {"virtualCluster": "vc1", "priority": 2, "pinnedCellId": "pin1",
                "chipNumber": 4,
                "affinityGroup": {"name": "gp",
                                  "members": [{"podNumber": 2, "chipNumber": 4}]}}
        origins = []
        for i in range(2):
            _, info = schedule_and_allocate(algo, make_pod(f"gp-{i}", spec))
            origins.append(tuple(int(x) for x in info.node.split("/")[-1].split("-")))
        # pin1 is the 2x2x2 cube at origin (0,0,0): hosts (0,0,0) and (0,0,1)
        assert sorted(origins) == [(0, 0, 0), (0, 0, 1)]

    def test_generic_chain_scheduling(self, algo):
        pod = make_pod("pv4", {"virtualCluster": "vc1", "priority": 0,
                               "chipType": "v4-chip", "chipNumber": 8})
        _, info = schedule_and_allocate(algo, pod)
        assert info.cell_chain == "v4-node-pool"
        assert sorted(info.leaf_cell_isolation) == list(range(8))

    def test_any_leaf_cell_type(self, algo):
        pod = make_pod("pany", {"virtualCluster": "vc2", "priority": 0, "chipNumber": 8})
        _, info = schedule_and_allocate(algo, pod)
        assert info.cell_chain == "v5e-8"  # only chain with 8-chip nodes in vc2

    def test_opportunistic_pod(self, algo):
        pod = make_pod("opp", {"virtualCluster": "vc1", "priority": -1,
                               "chipType": "v5p-chip", "chipNumber": 4})
        _, info = schedule_and_allocate(algo, pod)
        assert info.cell_chain == "v5p-64"
        g = algo.get_affinity_group("default/opp")  # default group name: ns/pod
        assert g.status.state == GROUP_ALLOCATED
        # OT usage shows up as a fake -opp virtual cell in the VC status
        vc_status = algo.get_virtual_cluster_status("vc1")
        assert any(s.cell_address.endswith("-opp") for s in vc_status)

    def test_delete_pod_frees_cells(self, algo):
        pod = make_pod("p1", {"virtualCluster": "vc2", "priority": 0,
                              "chipType": "v5e-chip", "chipNumber": 8})
        bp, _ = schedule_and_allocate(algo, pod)
        algo.delete_allocated_pod(bp)
        with pytest.raises(api.WebServerError):
            algo.get_affinity_group("p1")
        # all cells free again: scheduling works again
        pod2 = make_pod("p2", {"virtualCluster": "vc2", "priority": 0,
                               "chipType": "v5e-chip", "chipNumber": 8})
        schedule_and_allocate(algo, pod2)

    def test_vc_safety_capacity(self, algo):
        # vc2 owns 2x 2x2x2 (16 chips) of v5p-64; requesting a third 2x2x2's
        # worth beyond its share must wait, not steal vc1's cells
        spec = {"virtualCluster": "vc2", "priority": 0, "chipType": "v5p-chip",
                "chipNumber": 4}
        for i in range(4):  # 16 chips = vc2's full share
            schedule_and_allocate(algo, make_pod(f"s-{i}", {
                **spec, "affinityGroup": {"name": f"s-{i}",
                                          "members": [{"podNumber": 1, "chipNumber": 4}]}}))
        r = algo.schedule(make_pod("overflow", spec), all_node_names(algo), FILTERING_PHASE)
        assert r.pod_wait_info is not None


class TestUserErrors:
    def test_unknown_vc(self, algo):
        pod = make_pod("bad", {"virtualCluster": "ghost", "priority": 0, "chipNumber": 1})
        with pytest.raises(api.WebServerError) as e:
            algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)
        assert e.value.code == 400

    def test_unknown_leaf_cell_type(self, algo):
        pod = make_pod("bad", {"virtualCluster": "vc1", "priority": 0,
                               "chipType": "h100", "chipNumber": 1})
        with pytest.raises(api.WebServerError) as e:
            algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)
        assert e.value.code == 400

    def test_type_not_in_vc(self, algo):
        pod = make_pod("bad", {"virtualCluster": "vc1", "priority": 0,
                               "chipType": "v5e-chip", "chipNumber": 1})
        with pytest.raises(api.WebServerError):
            algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)

    def test_opportunistic_on_pinned_cell(self, algo):
        pod = make_pod("bad", {"virtualCluster": "vc1", "priority": -1,
                               "pinnedCellId": "pin1", "chipNumber": 1})
        with pytest.raises(api.WebServerError):
            algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)

    def test_missing_annotation(self, algo):
        pod = Pod(name="na", uid="na")
        with pytest.raises(api.WebServerError):
            algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)

    def test_invalid_priority(self, algo):
        pod = make_pod("bad", {"virtualCluster": "vc1", "priority": 1001, "chipNumber": 1})
        with pytest.raises(api.WebServerError):
            algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)

    def test_too_many_pods_in_group(self, algo):
        spec = {"virtualCluster": "vc2", "priority": 0, "chipType": "v5e-chip",
                "chipNumber": 4,
                "affinityGroup": {"name": "g1",
                                  "members": [{"podNumber": 1, "chipNumber": 4}]}}
        schedule_and_allocate(algo, make_pod("g1-0", spec))
        with pytest.raises(api.WebServerError):
            algo.schedule(make_pod("g1-1", spec), all_node_names(algo), FILTERING_PHASE)

    # --- remaining bad-request shapes of the reference's failure table
    # (hived_algorithm_test.go:245-293) and spec validation
    # (internal/utils.go:230-289); every one must recover as HTTP 4xx ---

    def _assert_bad_request(self, algo, spec_dict):
        with pytest.raises(api.WebServerError) as e:
            algo.schedule(make_pod("bad", spec_dict), all_node_names(algo),
                          FILTERING_PHASE)
        assert 400 <= e.value.code < 500, e.value.code

    def test_unknown_pinned_cell_guaranteed(self, algo):
        # reference pod14: invalid pinned cell
        self._assert_bad_request(algo, {
            "virtualCluster": "vc1", "priority": 1,
            "pinnedCellId": "surprise!", "chipNumber": 1})

    def test_pod_not_in_group_members(self, algo):
        # reference pod11/pod12 family: invalid affinity group configuration
        self._assert_bad_request(algo, {
            "virtualCluster": "vc1", "priority": 0, "chipNumber": 3,
            "affinityGroup": {"name": "mismatch",
                              "members": [{"podNumber": 2, "chipNumber": 4}]}})

    def test_priority_below_opportunistic(self, algo):
        self._assert_bad_request(algo, {
            "virtualCluster": "vc1", "priority": -2, "chipNumber": 1})

    def test_non_positive_leaf_cell_number(self, algo):
        self._assert_bad_request(algo, {
            "virtualCluster": "vc1", "priority": 0, "chipNumber": 0})

    def test_non_positive_pod_number_in_members(self, algo):
        self._assert_bad_request(algo, {
            "virtualCluster": "vc1", "priority": 0, "chipNumber": 4,
            "affinityGroup": {"name": "zero",
                              "members": [{"podNumber": 0, "chipNumber": 4}]}})

    def test_empty_virtual_cluster(self, algo):
        self._assert_bad_request(algo, {"priority": 0, "chipNumber": 1})

    def test_empty_group_name(self, algo):
        self._assert_bad_request(algo, {
            "virtualCluster": "vc1", "priority": 0, "chipNumber": 4,
            "affinityGroup": {"name": "",
                              "members": [{"podNumber": 1, "chipNumber": 4}]}})

    def test_malformed_annotation(self, algo):
        from hivedscheduler_tpu.api import constants as C
        from hivedscheduler_tpu.k8s.types import Container

        pod = Pod(
            name="mal", uid="mal",
            annotations={C.ANNOTATION_POD_SCHEDULING_SPEC: "{not: [valid"},
            containers=[Container(
                resource_limits={C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})],
        )
        with pytest.raises(api.WebServerError) as e:
            algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)
        assert 400 <= e.value.code < 500

    def test_user_errors_leave_no_state(self, algo):
        """A rejected request must not leak a group or touch the free lists."""
        before = {
            (chain, lv): len(ccl[lv])
            for chain, ccl in algo.free_cell_list.items() for lv in sorted(ccl)
        }
        for spec_dict in (
            {"virtualCluster": "ghost", "priority": 0, "chipNumber": 1},
            {"virtualCluster": "vc1", "priority": 1001, "chipNumber": 1},
            {"virtualCluster": "vc1", "priority": 1,
             "pinnedCellId": "surprise!", "chipNumber": 1},
        ):
            with pytest.raises(api.WebServerError):
                algo.schedule(make_pod("bad", spec_dict), all_node_names(algo),
                              FILTERING_PHASE)
        after = {
            (chain, lv): len(ccl[lv])
            for chain, ccl in algo.free_cell_list.items() for lv in sorted(ccl)
        }
        assert after == before
        assert algo.get_all_affinity_groups() == []


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------


class TestStatefulPreemption:
    def _fill_vc2_v5p(self, algo, priority=1):
        """Fill vc2's entire v5p share (2x 2x2x2) with low-priority pods."""
        pods = []
        for i in range(4):
            spec = {"virtualCluster": "vc2", "priority": priority,
                    "chipType": "v5p-chip", "chipNumber": 4,
                    "affinityGroup": {"name": f"low-{i}",
                                      "members": [{"podNumber": 1, "chipNumber": 4}]}}
            bp, info = schedule_and_allocate(algo, make_pod(f"low-{i}", spec))
            pods.append(bp)
        return pods

    def test_intra_vc_preemption_lifecycle(self, algo):
        victims = self._fill_vc2_v5p(algo, priority=1)
        spec_hi = {"virtualCluster": "vc2", "priority": 100, "chipType": "v5p-chip",
                   "chipNumber": 4,
                   "affinityGroup": {"name": "hi",
                                     "members": [{"podNumber": 4, "chipNumber": 4}]}}
        hi_pod = make_pod("hi-0", spec_hi)
        # Filtering phase: victims found but no preemption state created
        r = algo.schedule(hi_pod, all_node_names(algo), FILTERING_PHASE)
        assert r.pod_preempt_info is not None
        assert "hi" not in {g.name for g in algo.get_all_affinity_groups()}
        # Preempting phase: preemptor reserves cells
        r = algo.schedule(hi_pod, all_node_names(algo), PREEMPTING_PHASE)
        assert r.pod_preempt_info is not None
        g = algo.get_affinity_group("hi")
        assert g.status.state == GROUP_PREEMPTING
        # some victim group must be BeingPreempted now
        states = {x.name: x.status.state for x in algo.get_all_affinity_groups()}
        assert GROUP_BEING_PREEMPTED in states.values()
        # victims die -> cells Reserving -> Reserved
        for v in victims:
            algo.delete_allocated_pod(v)
        # preemptor pods get scheduled now: no victims left
        for i in range(4):
            p = make_pod(f"hi-{i}", spec_hi, uid=f"hi-{i}")
            r = algo.schedule(p, all_node_names(algo), FILTERING_PHASE)
            assert r.pod_bind_info is not None
            algo.add_allocated_pod(new_binding_pod(p, r.pod_bind_info))
        g = algo.get_affinity_group("hi")
        assert g.status.state == GROUP_ALLOCATED

    def test_preemption_canceled_when_preemptor_deleted(self, algo):
        self._fill_vc2_v5p(algo, priority=1)
        spec_hi = {"virtualCluster": "vc2", "priority": 100, "chipType": "v5p-chip",
                   "chipNumber": 4,
                   "affinityGroup": {"name": "hi",
                                     "members": [{"podNumber": 4, "chipNumber": 4}]}}
        hi_pod = make_pod("hi-0", spec_hi)
        algo.schedule(hi_pod, all_node_names(algo), PREEMPTING_PHASE)
        assert algo.get_affinity_group("hi").status.state == GROUP_PREEMPTING
        # preemptor pod deleted before victims die: preemption canceled,
        # cells return to the victims
        algo.delete_unallocated_pod(hi_pod)
        assert "hi" not in {g.name for g in algo.get_all_affinity_groups()}
        states = {x.name: x.status.state for x in algo.get_all_affinity_groups()}
        # no group is still Preempting; victims keep their cells (the reference
        # leaves them in BeingPreempted state after a canceled preemption)
        assert GROUP_PREEMPTING not in states.values()
        for ccl in algo.full_cell_list["v5p-64"].values():
            for c in ccl:
                assert c.state in (CELL_USED, CELL_FREE)

    def test_preemptor_displaced_by_higher_priority(self, algo):
        """Cell e3/e6: a higher-priority preemptor overwrites a lower-priority
        preemptor's Reserving cells; the loser goes back to Pending (AG e5)
        while the victims stay BeingPreempted (reference:
        hived_algorithm.go:736-741)."""
        self._fill_vc2_v5p(algo, priority=1)
        spec_mid = {"virtualCluster": "vc2", "priority": 50, "chipType": "v5p-chip",
                    "chipNumber": 4,
                    "affinityGroup": {"name": "mid",
                                      "members": [{"podNumber": 4, "chipNumber": 4}]}}
        algo.schedule(make_pod("mid-0", spec_mid), all_node_names(algo),
                      PREEMPTING_PHASE)
        assert algo.get_affinity_group("mid").status.state == GROUP_PREEMPTING
        # a higher-priority preemptor wants the same (only) share of vc2
        spec_hi = {"virtualCluster": "vc2", "priority": 100, "chipType": "v5p-chip",
                   "chipNumber": 4,
                   "affinityGroup": {"name": "hi",
                                     "members": [{"podNumber": 4, "chipNumber": 4}]}}
        r = algo.schedule(make_pod("hi-0", spec_hi), all_node_names(algo),
                          PREEMPTING_PHASE)
        assert r.pod_preempt_info is not None
        names = {g.name for g in algo.get_all_affinity_groups()}
        assert "mid" not in names  # loser preemptor back to Pending
        assert algo.get_affinity_group("hi").status.state == GROUP_PREEMPTING
        # victims keep running (BeingPreempted), their cells Reserving for hi
        states = {x.name: x.status.state for x in algo.get_all_affinity_groups()}
        assert GROUP_BEING_PREEMPTED in states.values()

    def test_preemption_canceled_when_allocation_wins(self, algo):
        """Cell e8(i): an Allocated group claims cells Reserved by a
        lower-priority preemptor — the preemptor is canceled (AG e5) and the
        winner allocates. Realized, as in the reference, via the
        Preempting-phase overlap cancellation followed by a bind (no victims
        remain once the cells are merely Reserved)."""
        victims = self._fill_vc2_v5p(algo, priority=1)
        spec_mid = {"virtualCluster": "vc2", "priority": 50, "chipType": "v5p-chip",
                    "chipNumber": 4,
                    "affinityGroup": {"name": "mid",
                                      "members": [{"podNumber": 4, "chipNumber": 4}]}}
        algo.schedule(make_pod("mid-0", spec_mid), all_node_names(algo),
                      PREEMPTING_PHASE)
        # victims die: mid's cells go Reserving -> Reserved
        for v in victims:
            algo.delete_allocated_pod(v)
        reserved = [
            c
            for ccl in algo.full_cell_list["v5p-64"].values()
            for c in ccl
            if c.state == CELL_RESERVED
        ]
        assert reserved, "expected Reserved cells held by the mid preemptor"
        # higher-priority group takes the Reserved cells: no pods to kill, so
        # the overlap cancellation leaves a directly bindable placement
        spec_win = {"virtualCluster": "vc2", "priority": 100, "chipType": "v5p-chip",
                    "chipNumber": 4,
                    "affinityGroup": {"name": "win",
                                      "members": [{"podNumber": 4, "chipNumber": 4}]}}
        r = algo.schedule(make_pod("win-0", spec_win), all_node_names(algo),
                          PREEMPTING_PHASE)
        assert "mid" not in {g.name for g in algo.get_all_affinity_groups()}
        assert r.pod_bind_info is not None, (
            "with victims gone the winner should bind, not preempt"
        )
        algo.add_allocated_pod(new_binding_pod(make_pod("win-0", spec_win),
                                               r.pod_bind_info))
        assert algo.get_affinity_group("win").status.state == GROUP_ALLOCATED
        used = [
            c
            for ccl in algo.full_cell_list["v5p-64"].values()
            for c in ccl
            if c.state == CELL_USED
        ]
        assert used, "winner's cells must be Used"
        assert all(c.state != CELL_RESERVED for ccl in
                   algo.full_cell_list["v5p-64"].values() for c in ccl)

    def test_opportunistic_preempted_by_guaranteed(self, algo):
        # fill vc1's v5p share with an opportunistic gang (uses free cells)
        spec_opp = {"virtualCluster": "vc1", "priority": -1, "chipType": "v5p-chip",
                    "chipNumber": 4,
                    "affinityGroup": {"name": "opp",
                                      "members": [{"podNumber": 16, "chipNumber": 4}]}}
        for i in range(16):  # fill the whole v5p-64 cube
            schedule_and_allocate(algo, make_pod(f"opp-{i}", spec_opp))
        # guaranteed gang in vc1 wants its 4x4x2: must preempt the OT pods
        spec_g = {"virtualCluster": "vc1", "priority": 0, "chipType": "v5p-chip",
                  "chipNumber": 4,
                  "affinityGroup": {"name": "guar",
                                    "members": [{"podNumber": 8, "chipNumber": 4}]}}
        r = algo.schedule(make_pod("guar-0", spec_g), all_node_names(algo),
                          PREEMPTING_PHASE)
        assert r.pod_preempt_info is not None
        assert len(r.pod_preempt_info.victim_pods) > 0


class TestLazyPreemption:
    def test_lazy_preemption(self, algo):
        # g1 in vc2 with lazy preemption enabled takes one 2x2x2
        spec1 = {"virtualCluster": "vc2", "priority": 1, "chipType": "v5p-chip",
                 "chipNumber": 4, "lazyPreemptionEnable": True,
                 "affinityGroup": {"name": "lazy1",
                                   "members": [{"podNumber": 2, "chipNumber": 4}]}}
        for i in range(2):
            schedule_and_allocate(algo, make_pod(f"lazy1-{i}", spec1))
        # fill rest of vc2's v5p share
        spec2 = {"virtualCluster": "vc2", "priority": 1, "chipType": "v5p-chip",
                 "chipNumber": 4, "lazyPreemptionEnable": True,
                 "affinityGroup": {"name": "lazy2",
                                   "members": [{"podNumber": 2, "chipNumber": 4}]}}
        for i in range(2):
            schedule_and_allocate(algo, make_pod(f"lazy2-{i}", spec2))
        # higher-priority group in vc2: lazy-preempts instead of killing
        spec_hi = {"virtualCluster": "vc2", "priority": 50, "chipType": "v5p-chip",
                   "chipNumber": 4,
                   "affinityGroup": {"name": "hi",
                                     "members": [{"podNumber": 2, "chipNumber": 4}]}}
        r = algo.schedule(make_pod("hi-0", spec_hi), all_node_names(algo),
                          FILTERING_PHASE)
        # lazy preemption: the high-priority group gets a placement WITHOUT
        # binding victims (they are demoted to opportunistic instead)
        assert r.pod_bind_info is not None
        lazy_preempted = [g for g in algo.get_all_affinity_groups()
                          if g.status.lazy_preemption_status is not None]
        assert len(lazy_preempted) >= 1
        assert lazy_preempted[0].status.lazy_preemption_status.preemptor == "hi"


# ---------------------------------------------------------------------------
# suggested nodes
# ---------------------------------------------------------------------------


class TestSuggestedNodes:
    def test_ignore_suggested_default(self, algo):
        # default ignoreK8sSuggestedNodes=True: schedules outside suggestions
        pod = make_pod("p", {"virtualCluster": "vc2", "priority": 0,
                             "chipType": "v5e-chip", "chipNumber": 1})
        r = algo.schedule(pod, [], FILTERING_PHASE)
        assert r.pod_bind_info is not None

    def test_respect_suggested_nodes(self, algo):
        pod = make_pod("p", {"virtualCluster": "vc2", "priority": 0,
                             "chipType": "v5e-chip", "chipNumber": 1,
                             "ignoreK8sSuggestedNodes": False})
        r = algo.schedule(pod, [], FILTERING_PHASE)
        assert r.pod_wait_info is not None
        r = algo.schedule(pod, ["v5e-host0/0-0"], FILTERING_PHASE)
        assert r.pod_bind_info is not None


# ---------------------------------------------------------------------------
# bad nodes / doomed bad cells
# ---------------------------------------------------------------------------


class TestBadNodes:
    def test_bad_node_avoided(self, algo):
        algo.delete_node(Node(name="v5e-host0/0-0"))
        pod = make_pod("p", {"virtualCluster": "vc2", "priority": 0,
                             "chipType": "v5e-chip", "chipNumber": 1})
        r = algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)
        assert r.pod_wait_info is not None
        assert "bad node" in r.pod_wait_info.reason
        # node comes back
        algo.add_node(Node(name="v5e-host0/0-0"))
        r = algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)
        assert r.pod_bind_info is not None

    def test_doomed_bad_cell_binding(self, algo):
        # kill the v5e host: vc2's v5e-8 cell is doomed to be bad
        algo.delete_node(Node(name="v5e-host0/0-0"))
        vc2 = algo.get_virtual_cluster_status("vc2")
        doomed = [s for s in vc2 if s.cell_type == "v5e-8" and s.cell_healthiness == api.CELL_BAD]
        assert len(doomed) == 1
        assert doomed[0].physical_cell is not None
        # healthy again: doomed binding released
        algo.add_node(Node(name="v5e-host0/0-0"))
        vc2 = algo.get_virtual_cluster_status("vc2")
        assert all(s.cell_healthiness == api.CELL_HEALTHY for s in vc2 if s.cell_type == "v5e-8")

    def test_allocated_group_insists_on_bad_node(self, algo):
        pod = make_pod("p", {"virtualCluster": "vc2", "priority": 0,
                             "chipType": "v5e-chip", "chipNumber": 8})
        bp, info = schedule_and_allocate(algo, pod)
        algo.delete_node(Node(name=info.node))
        # a new pod of the (full) allocated group is a user error; the group
        # itself insists its placement despite the now-bad node
        with pytest.raises(api.WebServerError):
            algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)
        assert algo.get_affinity_group("default/p").status.state == GROUP_ALLOCATED
        # after the group is gone, the bad node blocks new scheduling
        algo.delete_allocated_pod(bp)
        r = algo.schedule(pod, all_node_names(algo), FILTERING_PHASE)
        assert r.pod_wait_info is not None  # node is bad now


class TestSafeRelaxedBuddyAlloc:
    def test_split_higher_level_on_bad_cells(self, algo):
        # make both hosts of vc2's natural first 2x2x2 allocation target bad
        # at z in {0,1} side; the allocator must split a higher-level cell
        # while respecting vc1's guarantees
        algo.delete_node(Node(name="v5p-pod0/0-0-0"))
        spec = {"virtualCluster": "vc2", "priority": 1, "chipType": "v5p-chip",
                "chipNumber": 4,
                "affinityGroup": {"name": "g", "members": [{"podNumber": 2, "chipNumber": 4}]}}
        origins = []
        for i in range(2):
            _, info = schedule_and_allocate(algo, make_pod(f"g-{i}", spec))
            origins.append(info.node)
        assert "v5p-pod0/0-0-0" not in origins


# ---------------------------------------------------------------------------
# recovery / reconfiguration
# ---------------------------------------------------------------------------


class TestRecovery:
    def test_crash_recovery_replay(self, algo):
        spec = {"virtualCluster": "vc1", "priority": 5, "chipType": "v5p-chip",
                "chipNumber": 4,
                "affinityGroup": {"name": "g32",
                                  "members": [{"podNumber": 8, "chipNumber": 4}]}}
        bound = []
        for i in range(8):
            bp, _ = schedule_and_allocate(algo, make_pod(f"g32-{i}", spec))
            bound.append(bp)
        placement_before = algo.get_affinity_group("g32").status.physical_placement

        # "restart": a fresh algorithm instance, replay bound pods
        h2 = HivedAlgorithm(load_config(FIXTURE))
        set_healthy_nodes(h2)
        for bp in bound:
            h2.add_allocated_pod(bp)
        g = h2.get_affinity_group("g32")
        assert g.status.state == GROUP_ALLOCATED
        assert g.status.physical_placement == placement_before
        assert g.status.lazy_preemption_status is None
        # the recovered group occupies real cells: vc1 cannot double-book
        r = h2.schedule(make_pod("extra", {
            "virtualCluster": "vc1", "priority": 5, "chipType": "v5p-chip",
            "chipNumber": 4,
            "affinityGroup": {"name": "extra",
                              "members": [{"podNumber": 8, "chipNumber": 4}]}}),
            all_node_names(h2), FILTERING_PHASE)
        assert r.pod_wait_info is not None

    def test_reconfiguration_shrunk_vc_lazy_preempts(self, algo, tmp_path):
        spec = {"virtualCluster": "vc1", "priority": 5, "chipType": "v5p-chip",
                "chipNumber": 4,
                "affinityGroup": {"name": "g32",
                                  "members": [{"podNumber": 8, "chipNumber": 4}]}}
        bound = [schedule_and_allocate(algo, make_pod(f"g32-{i}", spec))[0]
                 for i in range(8)]

        # reconfigure: vc1 loses its v5p-4x4x2 (moved to vc2)
        import yaml
        with open(FIXTURE) as f:
            cfg = yaml.safe_load(f)
        cfg["virtualClusters"]["vc1"]["virtualCells"] = [
            {"cellType": "v4-node-pool.v4-node", "cellNumber": 2}]
        cfg["virtualClusters"]["vc2"]["virtualCells"].append(
            {"cellType": "v5p-64.v5p-4x4x2", "cellNumber": 1})
        new_path = tmp_path / "reconf.yaml"
        new_path.write_text(yaml.safe_dump(cfg))

        h2 = HivedAlgorithm(load_config(str(new_path)))
        set_healthy_nodes(h2)
        for bp in bound:
            h2.add_allocated_pod(bp)
        g = h2.get_affinity_group("g32")
        # group still running (work-preserving) but lazy-preempted out of VC
        assert g.status.state == GROUP_ALLOCATED
        assert g.status.lazy_preemption_status is not None


class TestInvalidInitialAssignment:
    def test_vc_overcommit_panics(self, tmp_path):
        import yaml
        with open(FIXTURE) as f:
            cfg = yaml.safe_load(f)
        cfg["virtualClusters"]["vc2"]["virtualCells"] = [
            {"cellType": "v5p-64.v5p-4x4x2", "cellNumber": 2}]  # + vc1's 1 + pin = overcommit
        path = tmp_path / "bad.yaml"
        path.write_text(yaml.safe_dump(cfg))
        with pytest.raises(AssertionError, match="Illegal initial VC assignment"):
            HivedAlgorithm(load_config(str(path)))

    def test_vc_chain_missing_panics(self, tmp_path):
        import yaml
        with open(FIXTURE) as f:
            cfg = yaml.safe_load(f)
        cfg["physicalCluster"]["physicalCells"] = [
            c for c in cfg["physicalCluster"]["physicalCells"]
            if c.get("cellType") != "v5e-8"]
        path = tmp_path / "bad2.yaml"
        path.write_text(yaml.safe_dump(cfg))
        with pytest.raises(AssertionError):
            HivedAlgorithm(load_config(str(path)))


class TestSchedulingPolicy:
    def test_spread_policy_prefers_empty_nodes(self, tmp_path):
        import yaml

        with open(FIXTURE) as f:
            cfg = yaml.safe_load(f)
        cfg["virtualClusters"]["vc1"]["schedulingPolicy"] = "spread"
        path = tmp_path / "spread.yaml"
        path.write_text(yaml.safe_dump(cfg))
        h = HivedAlgorithm(load_config(str(path)))
        set_healthy_nodes(h)
        # two 4-chip v4 pods in vc1: spread lands them on different nodes
        nodes = set()
        for i in range(2):
            _, info = schedule_and_allocate(h, make_pod(f"s-{i}", {
                "virtualCluster": "vc1", "priority": 0,
                "chipType": "v4-chip", "chipNumber": 4,
                "affinityGroup": {"name": f"s-{i}",
                                  "members": [{"podNumber": 1, "chipNumber": 4}]}}))
            nodes.add(info.node)
        assert len(nodes) == 2  # spread across nodes

        # default pack policy packs both onto one node
        h2 = HivedAlgorithm(load_config(FIXTURE))
        set_healthy_nodes(h2)
        nodes2 = set()
        for i in range(2):
            _, info = schedule_and_allocate(h2, make_pod(f"p-{i}", {
                "virtualCluster": "vc1", "priority": 0,
                "chipType": "v4-chip", "chipNumber": 4,
                "affinityGroup": {"name": f"p-{i}",
                                  "members": [{"podNumber": 1, "chipNumber": 4}]}}))
            nodes2.add(info.node)
        assert len(nodes2) == 1  # packed

    def test_unknown_policy_rejected(self, tmp_path):
        import yaml

        with open(FIXTURE) as f:
            cfg = yaml.safe_load(f)
        cfg["virtualClusters"]["vc1"]["schedulingPolicy"] = "chaotic"
        path = tmp_path / "bad.yaml"
        path.write_text(yaml.safe_dump(cfg))
        with pytest.raises(ValueError, match="unknown schedulingPolicy"):
            HivedAlgorithm(load_config(str(path)))


class TestSuggestedNodesPreemption:
    def test_preemption_canceled_when_placement_leaves_suggested_set(self, algo):
        """Reference behavior (schedulePodFromExistingGroup): a Preempting
        group whose placement is no longer within the Preempting-phase
        suggested nodes cancels and reschedules; in the Filtering phase it
        insists."""
        # fill vc2's v5e host with a low-priority pod (not ignoring suggestions)
        lo = make_pod("lo", {"virtualCluster": "vc2", "priority": 1,
                             "chipType": "v5e-chip", "chipNumber": 8,
                             "ignoreK8sSuggestedNodes": False})
        schedule_and_allocate(algo, lo)
        hi = make_pod("hi", {"virtualCluster": "vc2", "priority": 100,
                             "chipType": "v5e-chip", "chipNumber": 8,
                             "ignoreK8sSuggestedNodes": False})
        r = algo.schedule(hi, all_node_names(algo), PREEMPTING_PHASE)
        assert r.pod_preempt_info is not None
        assert algo.get_affinity_group("default/hi").status.state == GROUP_PREEMPTING
        # Filtering phase with the host absent from suggestions: preemption
        # is NOT canceled (only Preempting-phase suggestions count)
        others = [n for n in all_node_names(algo) if n != "v5e-host0/0-0"]
        algo.schedule(hi, others, FILTERING_PHASE)
        assert "default/hi" in {g.name for g in algo.get_all_affinity_groups()}
        # Preempting phase without the host: preemption canceled
        r = algo.schedule(hi, others, PREEMPTING_PHASE)
        groups = {g.name for g in algo.get_all_affinity_groups()}
        assert "default/hi" not in groups or (
            algo.get_affinity_group("default/hi").status.state != GROUP_PREEMPTING
        )
