"""Paged KV cache (models/serving.py page_size > 0).

The load-bearing property is the differential: a paged engine's every
stream must be TOKEN-EXACT vs the dense ragged reference
(``HIVED_PAGED_KV=0`` / ``page_size=0``) under every composition — prefix
sharing with copy-on-write, chunked prefill, fused decode windows with EOS
at the window boundary, int8 KV, sampling, speculative serving — plus the
allocator's own books: admission gated on block availability, pool
exhaustion degrading reclaim-then-preempt, and the free-list/refcount
invariants (``chaos.invariants.check_block_pool``) holding after every
engine step."""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.chaos.invariants import (  # noqa: E402
    InvariantViolation,
    check_block_pool,
)
from hivedscheduler_tpu.models import decode, serving, transformer as tm  # noqa: E402
from hivedscheduler_tpu.models.speculative import (  # noqa: E402
    SpecDecodeConfig,
    derive_draft_config,
)


def cfg_of(**kw):
    base = dict(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=128, max_seq_len=128, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def vanilla(params, cfg, prompt, n):
    out = decode.generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, n,
        max_len=len(prompt) + n,
    )
    return [int(t) for t in np.asarray(out)[0]]


PROMPTS = [[5, 9, 2], [17, 3, 88, 41, 7], [1], [100, 22, 63, 4]]
BUDGETS = [6, 4, 8, 5]

# a 10-token shared "system prompt": with page_size=8 it spans one full
# block + a partial block, so block-chunk matching AND mid-block COW both
# exercise
SYSTEM = [7, 11, 23, 42, 5, 9, 81, 2, 64, 33]


def run_both(params, cfg, prompts=PROMPTS, budgets=BUDGETS, *, checked=True,
             **kw):
    """The differential harness: run the same load through the paged engine
    and the dense reference engine, assert stream equality, return the
    paged engine (for counter/invariant asserts). ``checked`` runs the
    block-pool invariant after every paged step."""
    outs = []
    engines = []
    for page_size in (8, 0):
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    page_size=page_size, **kw)
        reqs = [eng.submit(list(p), n) for p, n in zip(prompts, budgets)]
        while eng.step():
            if checked and page_size:
                check_block_pool(eng, "differential churn")
        outs.append([(r.tokens_out, r.finish_reason) for r in reqs])
        engines.append(eng)
    assert outs[0] == outs[1], "paged streams diverged from dense reference"
    return engines[0], outs[0]


class TestPagedDifferential:
    def test_interleaved_matches_dense_and_vanilla(self, setup):
        cfg, params = setup
        _, out = run_both(params, cfg)
        for (toks, _reason), p, n in zip(out, PROMPTS, BUDGETS):
            assert toks == vanilla(params, cfg, p, n)

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 15): heavy
    # variant; tier-1 cousins: test_interleaved_matches_dense_and_vanilla
    # (the broad paged differential) + the block-pool invariant seeds
    # (test_invariant_checker_catches_seeded_leak) + the dense prefix
    # exactness suite (tests/test_serving_prefix.py)
    def test_prefix_sharing_and_cow_mid_block(self, setup):
        """Three prompts sharing the 10-token system prefix: the second
        matches the cached blocks (one full + one partial), COWs the
        partial block mid-block at divergence, and every stream stays
        exact. Blocks are shared by REFERENCE: the hit must not copy the
        full block."""
        cfg, params = setup
        prompts = [SYSTEM + [100, 101], SYSTEM + [120, 90, 3],
                   SYSTEM + [100, 101, 55]]
        budgets = [5, 6, 4]
        eng, out = run_both(params, cfg, prompts, budgets,
                            prefix_cache_size=16)
        for (toks, _), p, n in zip(out, prompts, budgets):
            assert toks == vanilla(params, cfg, p, n)
        assert eng.prefix_block_hits >= 1, "no block was shared by reference"
        assert eng.blocks_cow >= 1, "mid-block divergence did not COW"
        check_block_pool(eng, "after prefix/COW load")

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): chunked x
    # paged composition variant; tier-1 cousins: the greedy paged
    # differential above + the dense chunked parity
    # (test_serving_chunked.py::test_chunked_matches_monolithic[4])
    def test_chunked_prefill_composition(self, setup):
        cfg, params = setup
        prompts = [SYSTEM + [100, 101], [17, 3, 88, 41, 7, 6, 2, 91, 55, 44],
                   SYSTEM + [120, 90, 3, 4, 8, 15]]
        budgets = [5, 4, 6]
        eng, out = run_both(params, cfg, prompts, budgets,
                            prefix_cache_size=16, prefill_chunk=3)
        for (toks, _), p, n in zip(out, prompts, budgets):
            assert toks == vanilla(params, cfg, p, n)
        assert eng.prefill_chunks_done > 0

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): paged twin
    # of the dense EOS-at-boundary sweep; tier-1 cousins: the dense
    # sweep (test_serving_multistep.py::TestFusedDecodeExactness) + the
    # paged fused-window collapse unit test
    # (test_fused_window_collapses_during_chunked_prefill below)
    def test_fused_window_eos_at_boundary(self, setup):
        """decode_steps=4 with the EOS probed inside the window, exactly AT
        the window boundary, and on the first post-window step (the
        test_serving_multistep pattern, on the paged engine)."""
        cfg, params = setup
        stream = vanilla(params, cfg, [5, 9, 2], 8)
        tested = 0
        for pos in (2, 3, 4):
            eos = stream[pos]
            if eos in stream[:pos]:
                continue  # would retire earlier: not the position under test
            eng, out = run_both(params, cfg, [[5, 9, 2]], [8],
                                decode_steps=4, eos_id=eos)
            assert out[0] == (stream[:pos + 1], "eos"), pos
            tested += 1
        assert tested, "every probe position degenerate — new model seed?"

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): int8
    # variant of the paged differential; tier-1 cousins: the greedy
    # paged differential above + the dense int8 guards
    # (test_serving_int8kv.py)
    def test_int8_kv_paged_matches_int8_dense(self, setup):
        cfg, params = setup
        run_both(params, cfg, kv_dtype="int8", prefix_cache_size=8)

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): sampled
    # variant of the paged differential; tier-1 cousins: the greedy
    # paged differential above + the dense sampled-reproducibility guard
    # (test_serving.py::test_sampled_streams_reproducible_under_interleaving)
    def test_sampled_paged_matches_sampled_dense(self, setup):
        """Counter-based keys make sampled streams a pure function of
        (seed, rid, prompt) — the cache layout must not leak into them."""
        cfg, params = setup
        run_both(params, cfg, temperature=0.9, top_k=20, seed=7)


class TestPagedAdmission:
    def test_admission_gated_on_block_availability(self, setup):
        """8 usable blocks, 17-token prompts (3 blocks each, growing to 4):
        at most two streams fit at once even though 4 slots exist, nothing
        is preempted, and every stream is exact — long-tail prompts no
        longer reserve max-length HBM, short pools just queue."""
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=4, max_len=64,
                                    page_size=8, num_blocks=9)
        reqs = [eng.submit([40 + i] * 17, 15) for i in range(4)]
        max_conc = 0
        while eng.step():
            check_block_pool(eng, "admission churn")
            max_conc = max(max_conc, sum(s is not None for s in eng.slots))
        assert max_conc <= 2, max_conc
        assert eng.pool_preempted == 0
        for i, r in enumerate(reqs):
            assert r.finish_reason == "length"
            assert r.tokens_out == vanilla(params, cfg, [40 + i] * 17, 15), i

    def test_pool_exhaustion_preempts_and_survivor_exact(self, setup):
        """Both streams admitted, then decode growth exhausts the pool:
        exactly one stream is truncated (finish_reason="preempted",
        counted), the survivor finishes token-exact, and every block
        returns to the free list."""
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=32,
                                    page_size=4, num_blocks=9)
        r1 = eng.submit([3] * 10, 20)
        r2 = eng.submit([9] * 10, 20)
        while eng.step():
            check_block_pool(eng, "exhaustion churn")
        reasons = sorted((r1.finish_reason, r2.finish_reason))
        assert reasons == ["length", "preempted"], reasons
        assert eng.pool_preempted == 1
        survivor, p = (r1, [3] * 10) if r1.finish_reason == "length" \
            else (r2, [9] * 10)
        assert survivor.tokens_out == vanilla(params, cfg, p, 20)
        assert len(eng._free) == eng.num_blocks - 1  # all blocks returned
        check_block_pool(eng, "after exhaustion drain")

    def test_cache_blocks_reclaimed_before_preemption(self, setup):
        """Pool pressure must evict LRU cached prefix blocks BEFORE
        touching live streams: a full cache plus a block-hungry load
        completes with zero preemptions."""
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=32,
                                    page_size=4, num_blocks=13,
                                    prefix_cache_size=16)
        warm = [eng.submit([60 + i] * 9, 2) for i in range(2)]
        eng.run_until_drained()
        assert all(w.done for w in warm)
        assert len(eng._prefix_cache) > 0  # cached blocks now pin the pool
        # 10-token prompts + 13 new tokens = 6 blocks each: both streams
        # fit the 12 usable blocks ONLY once the cached blocks are evicted
        big = [eng.submit([80 + i] * 10, 13) for i in range(2)]
        while eng.step():
            check_block_pool(eng, "reclaim churn")
        assert all(r.finish_reason == "length" for r in big)
        assert eng.pool_preempted == 0
        for i, r in enumerate(big):
            assert r.tokens_out == vanilla(params, cfg, [80 + i] * 10, 13)

    def test_env_kill_switch_forces_dense(self, setup, monkeypatch):
        """HIVED_PAGED_KV=0 is the reference-path contract: paging knobs
        are ignored and the dense engine serves (exactly)."""
        cfg, params = setup
        monkeypatch.setenv("HIVED_PAGED_KV", "0")
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    page_size=8, num_blocks=17)
        assert not eng.paged and eng.cache is not None
        r = eng.submit([5, 9, 2], 6)
        eng.run_until_drained()
        assert r.tokens_out == vanilla(params, cfg, [5, 9, 2], 6)

    def test_num_blocks_validation(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="cannot back one max_len"):
            serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                  page_size=8, num_blocks=8)

    def test_drain_returns_blocks(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    page_size=8)
        eng.submit([5, 9, 2], 40)
        eng.step()
        assert eng.blocks_in_use > 0
        assert eng.drain(deadline_s=0.0) is False  # truncates in-flight work
        assert eng.blocks_in_use == 0
        check_block_pool(eng, "after drain")

    def test_invariant_checker_catches_seeded_leak(self, setup):
        """The guard must actually guard: seed a leak / a double-alloc and
        check_block_pool has to raise."""
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    page_size=8)
        r = eng.submit([5, 9, 2], 4)
        eng.step()
        check_block_pool(eng, "clean")
        bid = eng._slot_bids[0][0]
        eng._free.append(bid)  # referenced AND free
        with pytest.raises(InvariantViolation, match="double-alloc"):
            check_block_pool(eng, "seeded")
        eng._free.remove(bid)
        eng._ref[bid] += 1  # refcount drifts from recount
        with pytest.raises(InvariantViolation, match="refcount"):
            check_block_pool(eng, "seeded")
        eng._ref[bid] -= 1
        eng.run_until_drained()
        assert r.done


class TestSpecDecodeFirstClass:
    @pytest.fixture(scope="class")
    def draft(self, setup):
        cfg, _params = setup
        dcfg = derive_draft_config(cfg, 1, 32)
        dparams = tm.init_params(dcfg, jax.random.PRNGKey(3))
        return SpecDecodeConfig(draft_params=dparams, draft_cfg=dcfg,
                                gamma=3)

    def test_spec_decode_kwarg_routes(self, setup, draft):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    spec_decode=draft)
        assert isinstance(eng, serving.SpeculativeServingEngine)
        assert eng.gamma == draft.gamma

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): spec x paged
    # x prefix triple-composition variant; tier-1 cousins: the greedy
    # paged differential (TestPagedDifferential) + the dense speculative
    # greedy exactness guards (test_serving_speculative.py)
    def test_spec_paged_greedy_exact_with_prefix(self, setup, draft):
        """First-class speculative serving on the paged cache: greedy
        streams bit-match vanilla, target prefix blocks are shared by
        reference (draft KV rides the entry as a dense copy), and the
        verify-round block rollback keeps the allocator's books clean."""
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    page_size=8, prefix_cache_size=8,
                                    spec_decode=draft)
        reqs = [eng.submit(list(p), n) for p, n in zip(PROMPTS, BUDGETS)]
        while eng.step():
            check_block_pool(eng, "spec churn")
        for r, p, n in zip(reqs, PROMPTS, BUDGETS):
            assert r.tokens_out == vanilla(params, cfg, p, n), r.rid
        hit = eng.submit(PROMPTS[0] + [77], 4)  # extends a cached prompt
        eng.run_until_drained()
        assert hit.tokens_out == vanilla(params, cfg, PROMPTS[0] + [77], 4)
        assert eng.prefix_block_hits >= 1
        check_block_pool(eng, "after spec prefix")

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 7): sampled
    # variant; the greedy spec paged differentials stay tier-1
    def test_spec_sampled_paged_matches_spec_dense(self, setup, draft):
        cfg, params = setup
        outs = []
        for page_size in (8, 0):
            eng = serving.ServingEngine(params, cfg, max_batch=2,
                                        max_len=64, page_size=page_size,
                                        temperature=0.8, top_k=30,
                                        spec_decode=draft)
            reqs = [eng.submit(list(p), n) for p, n in zip(PROMPTS, BUDGETS)]
            eng.run_until_drained()
            outs.append([r.tokens_out for r in reqs])
        assert outs[0] == outs[1]


class TestPagedUnits:
    """Fast host-side units: no engine stepping, no jit dispatch."""

    def test_block_coords_and_gather_mapping(self):
        from hivedscheduler_tpu.ops.attention import (
            block_coords,
            gather_block_kv,
        )
        pool = jnp.arange(4 * 4 * 2).reshape(4, 4, 2)  # [blocks, block, tail]
        table = jnp.asarray([[2, 0, 3]], jnp.int32)
        view = gather_block_kv(pool, table)  # [1, 12, 2]
        assert view.shape == (1, 12, 2)
        # logical position 1 lives in block 2 offset 1; position 9 in
        # block 3 offset 1 (entry 1 is trash)
        assert np.array_equal(np.asarray(view[0, 1]), np.asarray(pool[2, 1]))
        assert np.array_equal(np.asarray(view[0, 9]), np.asarray(pool[3, 1]))
        blk, off = block_coords(jnp.asarray([[1, 9, 99]], jnp.int32), table, 4)
        assert np.asarray(blk).tolist() == [[2, 3, 3]]  # 99 clamps to last
        assert np.asarray(off).tolist() == [[1, 1, 3]]

    def test_admission_math(self, setup):
        """needed = cover - floor(plen/page) (+1 spare when the first
        decode token opens a fresh block) — the documented admission
        formula, probed through _blocks_admit with a pinched free list."""
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    page_size=8)
        req = serving.Request(0, list(range(17)), 4)  # 17 tokens -> 3 blocks
        eng._free = [1, 2, 3]
        assert eng._blocks_admit(req, None)
        eng._free = [1, 2]
        assert not eng._blocks_admit(req, None)
        req16 = serving.Request(1, list(range(16)), 4)  # 16 -> 2 blocks + spare
        eng._free = [1, 2, 3]
        assert eng._blocks_admit(req16, None)
        eng._free = [1, 2]
        assert not eng._blocks_admit(req16, None)

    def test_store_prefix_registers_block_boundaries(self, setup):
        """Paged entries sit at every full-block boundary + the full
        prompt (the block-chunk rekey the dense pow2 scheme approximated)."""
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    page_size=8, prefix_cache_size=16)
        prompt = list(range(20))
        eng.slots[0] = serving.Request(0, prompt, 4)  # occupy the slot
        eng._slot_bids[0] = [eng._alloc_block() for _ in range(3)]
        for j, bid in enumerate(eng._slot_bids[0]):
            eng._table[0, j] = bid
        eng._store_prefix(0, prompt)
        lens = sorted(plen for _, plen in eng._prefix_cache.values())
        assert lens == [8, 16, 20]
        for key, (payload, plen) in eng._prefix_cache.items():
            assert len(payload) == -(-plen // 8)
        check_block_pool(eng, "after boundary store")

    def test_spec_decode_conflicting_args_raise(self, setup):
        cfg, params = setup
        dcfg = derive_draft_config(cfg, 1, 32)
        dparams = tm.init_params(dcfg, jax.random.PRNGKey(3))
        sd = SpecDecodeConfig(draft_params=dparams, draft_cfg=dcfg)
        with pytest.raises(ValueError, match="not both"):
            serving.SpeculativeServingEngine(
                params, cfg, dparams, dcfg, spec_decode=sd,
                max_batch=2, max_len=64)
        with pytest.raises(ValueError, match="needs a draft model"):
            serving.SpeculativeServingEngine(params, cfg, max_batch=2,
                                             max_len=64)

    def test_paged_dp_mesh_rejected(self, setup):
        """Blocks are fungible across slots — a dp-sharded pool cannot
        exist; the constructor must say so instead of mis-sharding."""
        cfg, params = setup
        from hivedscheduler_tpu.parallel import topology

        axes = topology.MeshAxes(dp=2)
        mesh = topology.make_mesh(axes, jax.devices("cpu")[:2])
        with pytest.raises(ValueError, match="dp"):
            serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                  page_size=8, mesh=mesh)


class TestAllocatorUnits:
    """Host-only allocator behaviors: no decode stepping, minimal jit."""

    def make_engine(self, setup, **kw):
        cfg, params = setup
        base = dict(max_batch=2, max_len=64, page_size=8)
        base.update(kw)
        return serving.ServingEngine(params, cfg, **base)

    def test_trim_blocks_returns_rejected_tail(self, setup):
        eng = self.make_engine(setup)
        eng.slots[0] = serving.Request(0, [1, 2, 3], 4)
        eng._ensure_writable(0, 0, 30)  # 4 blocks
        assert len(eng._slot_bids[0]) == 4
        free_before = len(eng._free)
        eng._trim_blocks(0, 17)  # keep ceil(17/8) = 3
        assert len(eng._slot_bids[0]) == 3
        assert len(eng._free) == free_before + 1
        assert eng._table[0, 3] == 0
        check_block_pool(eng, "after trim")

    def test_retire_frees_and_parks(self, setup):
        eng = self.make_engine(setup)
        eng.slots[0] = serving.Request(0, [1, 2, 3], 4)
        eng._ensure_writable(0, 0, 10)
        eng._retire(0)
        assert eng.slots[0] is None and not eng._slot_bids[0]
        assert all(b == 0 for b in eng._table[0])
        assert eng._host_len[0] == eng._park_pos
        assert len(eng._free) == eng.num_blocks - 1
        check_block_pool(eng, "after retire")

    def test_blocks_in_use_tracks_alloc_free(self, setup):
        eng = self.make_engine(setup)
        assert eng.blocks_in_use == 0
        a, b = eng._alloc_block(), eng._alloc_block()
        assert eng.blocks_in_use == 2
        eng._decref(a)
        assert eng.blocks_in_use == 1
        eng._decref(b)
        assert eng.blocks_in_use == 0

    def test_checker_catches_seeded_leak(self, setup):
        eng = self.make_engine(setup)
        bid = eng._alloc_block()
        eng._ref[bid] = 0  # unreferenced but not returned to the free list
        with pytest.raises(InvariantViolation, match="leaked"):
            check_block_pool(eng, "seeded leak")

    def test_checker_catches_table_drift(self, setup):
        eng = self.make_engine(setup)
        eng.slots[0] = serving.Request(0, [1, 2, 3], 4)
        eng._ensure_writable(0, 0, 10)
        eng._table[0, 0] = eng._table[0, 1]  # device view != owned bids
        with pytest.raises(InvariantViolation, match="table row"):
            check_block_pool(eng, "seeded drift")

    def test_block_gate_keeps_waiter_queued(self, setup):
        """A gated admission must NOT pop the waiter (head-of-line): the
        queue is intact and the request admits later when blocks free."""
        eng = self.make_engine(setup, max_len=24, num_blocks=4)  # 3 usable
        holder = serving.Request(9, [1] * 17, 4)  # 3 blocks
        eng.slots[0] = holder
        eng._slot_bids[0] = [eng._alloc_block() for _ in range(3)]
        for j, bid in enumerate(eng._slot_bids[0]):
            eng._table[0, j] = bid
        req = eng.submit([2] * 17, 4)
        eng._admit()
        assert eng.queue and eng.queue[0] is req  # still queued, still first
        eng._retire(0)
        eng._admit()
        assert req not in eng.queue and eng.slots[1] is req or eng.slots[0] is req

    def test_env_value_one_keeps_paging(self, setup, monkeypatch):
        monkeypatch.setenv("HIVED_PAGED_KV", "1")
        eng = self.make_engine(setup)
        assert eng.paged and eng.pool is not None

    def test_gather_scales_tail_shape(self):
        from hivedscheduler_tpu.ops.attention import gather_block_kv
        pool = jnp.arange(3 * 4 * 2, dtype=jnp.float32).reshape(3, 4, 2)
        scales = pool[..., 0]  # [blocks, block] — int8 scale layout minus H
        table = jnp.asarray([[1, 2]], jnp.int32)
        assert gather_block_kv(scales, table).shape == (1, 8)
        assert gather_block_kv(pool, table).shape == (1, 8, 2)

    def test_occupancy_gauge_exported(self, setup):
        from hivedscheduler_tpu.runtime.metrics import REGISTRY
        eng = self.make_engine(setup)
        eng.submit([5, 9, 2], 3)
        eng.step()
        assert "tpu_hive_serve_block_pool_occupancy" in REGISTRY.render()


class TestQueueAndWindowUnits:
    """Host-side queue/window behaviors under paging: no decode dispatch."""

    def test_shed_fires_while_block_gated(self, setup):
        """A waiter stuck behind the block gate still sheds on its
        queue-wait deadline — exhaustion must not turn the deadline off."""
        cfg, params = setup
        t = [0.0]
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=24,
                                    page_size=8, num_blocks=4,
                                    queue_timeout_s=5.0,
                                    clock=lambda: t[0])
        eng.slots[0] = serving.Request(9, [1] * 17, 4)  # holds all 3 blocks
        eng._slot_bids[0] = [eng._alloc_block() for _ in range(3)]
        for j, bid in enumerate(eng._slot_bids[0]):
            eng._table[0, j] = bid
        req = eng.submit([2] * 17, 4)
        eng._admit()
        assert eng.queue, "should be gated, not admitted"
        t[0] = 6.0
        eng._admit()
        assert req.finish_reason == "shed" and not eng.queue

    def test_match_prefix_clamp_guard_applies_paged(self, setup):
        """A cached prefix whose bucketed tail write would clamp against
        the arena is skipped (the same guard as dense — an offset bucket
        past max_len would silently mis-place the chunk)."""
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=24,
                                    page_size=8, prefix_cache_size=8)
        eng._prefix_cache[tuple(range(20))] = ((1, 2, 3), 20)
        # tail of 3 tokens buckets to 4: 20 + 4 = 24 <= max_len — OK
        assert eng._match_prefix(list(range(20)) + [9, 9, 9]) is not None
        # tail of 17 buckets to 24 (clamped): 20 + 24 > 24 — skipped
        assert eng._match_prefix(list(range(20)) + [9] * 17) is None

    def test_spec_decode_gamma_validation(self, setup):
        cfg, params = setup
        dcfg = derive_draft_config(cfg, 1, 32)
        dparams = tm.init_params(dcfg, jax.random.PRNGKey(3))
        sd = SpecDecodeConfig(draft_params=dparams, draft_cfg=dcfg, gamma=0)
        with pytest.raises(ValueError, match="gamma"):
            serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                  spec_decode=sd)

    def test_fused_window_collapses_during_chunked_prefill(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    page_size=8, decode_steps=8,
                                    prefill_chunk=2)
        eng.slots[0] = serving.Request(0, [1, 2, 3], 16)
        assert eng._fused_window([0]) == 8
        eng._prefilling[1] = ([4] * 6, 0, 0)  # chunk in flight elsewhere
        assert eng._fused_window([0]) == 1

    def test_request_latency_properties(self, setup):
        r = serving.Request(0, [1], 4)
        assert r.ttft_s is None and r.tpot_s is None and r.queue_wait_s is None
        r.submitted_at, r.admitted_at = 1.0, 2.0
        r.first_token_at, r.done_at = 3.0, 7.0
        r.tokens_out = [5, 6, 7]
        assert r.queue_wait_s == 1.0 and r.ttft_s == 2.0
        assert r.tpot_s == 2.0  # (7-3) / (3-1)

    def test_priority_insert_keeps_fifo_within_level(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=64,
                                    page_size=8)
        a = eng.submit([1], 2)
        b = eng.submit([2], 2, priority=5)
        c = eng.submit([3], 2, priority=5)
        d = eng.submit([4], 2)
        assert eng.queue == [b, c, a, d]
