"""Guard: the pinned workload-chaos seed replay
(tools/check_workload_seeds.py) runs clean, the episode plans are genuinely
deterministic per seed (what makes a pinned seed a faithful permanent
regression test), and the pinned set keeps covering the full fault ladder."""

import os
import random
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_workload_seeds.py")


def _load_tool():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_workload_seeds
    finally:
        sys.path.pop(0)
    return check_workload_seeds


@pytest.mark.slow
def test_pinned_seeds_replay_clean():
    proc = subprocess.run([sys.executable, TOOL], cwd=REPO,
                          capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 0, (
        f"check_workload_seeds failed:\n{proc.stdout}{proc.stderr}"
    )
    assert "OK" in proc.stdout


def test_episode_plans_are_deterministic(tmp_path):
    """Same seed => identical episode plan (no subprocesses spawned: the
    plan is drawn in __init__)."""
    from hivedscheduler_tpu.chaos.workload import (
        EPISODE_KINDS,
        WorkloadChaosHarness,
    )

    a = WorkloadChaosHarness(seed=9, workdir=str(tmp_path))
    b = WorkloadChaosHarness(seed=9, workdir=str(tmp_path))
    assert a.episodes == b.episodes
    for kind, step in a.episodes:
        assert kind in EPISODE_KINDS
        assert a.plan.min_step <= step <= a.steps - 2


def test_elastic_episode_plan_is_deterministic(tmp_path):
    """Same seed => identical kill/preempt steps for the elastic ladder
    episode (drawn in __init__; no subprocesses spawned)."""
    from hivedscheduler_tpu.chaos.workload import ElasticWorkloadHarness

    a = ElasticWorkloadHarness(seed=3, workdir=str(tmp_path))
    b = ElasticWorkloadHarness(seed=3, workdir=str(tmp_path))
    assert (a.kill_step, a.preempt_step) == (b.kill_step, b.preempt_step)
    assert a.checkpoint_every < a.kill_step < a.preempt_step <= a.steps - 2


def test_pinned_set_covers_the_full_fault_ladder(tmp_path):
    """The pinned seeds must keep covering every episode kind — a plan
    change that silently drops e.g. the hang rung from the replayed mix
    fails here instead of rotting coverage."""
    from hivedscheduler_tpu.chaos.workload import (
        EPISODE_KINDS,
        WorkloadFaultPlan,
    )

    tool = _load_tool()
    covered = set()
    for seed, episodes, _why in tool.PINNED_SEEDS:
        plan = WorkloadFaultPlan(episodes=episodes)
        for kind, _step in plan.draw(random.Random(seed), steps=8):
            covered.add(kind)
    assert covered == set(EPISODE_KINDS), (
        f"pinned seeds only cover {sorted(covered)}"
    )
