"""Guard: the pinned chaos-seed replay (tools/check_chaos_seeds.py) runs
clean, and the replay machinery is genuinely deterministic — the property
that makes a pinned seed a faithful permanent regression test."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_chaos_seeds.py")


def test_pinned_seeds_replay_clean():
    proc = subprocess.run([sys.executable, TOOL], cwd=REPO,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"check_chaos_seeds failed:\n{proc.stdout}{proc.stderr}"
    )
    assert "OK" in proc.stdout


def test_replay_is_deterministic():
    """Same seed, same plan => identical injector fault sequence and
    outcome — byte-equal reports (minus nothing: the report has no
    timestamps by design)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_chaos_seeds
    finally:
        sys.path.pop(0)
    a = check_chaos_seeds.replay(seed=3, schedules=5)
    b = check_chaos_seeds.replay(seed=3, schedules=5)
    assert a == b
    assert a["violations"] == []
