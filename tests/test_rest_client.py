"""REST KubeClient tests against a miniature in-process ApiServer that speaks
the K8s list/watch/bind HTTP protocol (chunked watch streams, Bind
subresource with annotation merge)."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from queue import Queue

import pytest

from hivedscheduler_tpu.k8s.rest import RestKubeClient
from hivedscheduler_tpu.k8s.types import Binding


class MiniApiServer:
    """Just enough of the K8s API: /api/v1/{nodes,pods} list+watch, pod GET,
    and the pods/{name}/binding subresource."""

    def __init__(self, port=0):
        self.nodes = {}
        self.pods = {}  # key ns/name -> k8s dict
        self.rv = 1
        self.watchers = []  # queues of event dicts
        self.lock = threading.Lock()
        # wire-request log (method, path-with-query) — the kind-e2e dry-run
        # derives the client's required RBAC verbs from this
        self.requests = []
        # failure injection: exact path (no query) -> list of HTTP status
        # codes; each matching request consumes one and fails with it
        # (tests/test_rest_failures.py drives the client's retry ladder)
        self.fail_next = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def parse_request(self):
                ok = super().parse_request()
                if ok:
                    with outer.lock:
                        outer.requests.append((self.command, self.path))
                return ok

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _maybe_fail(self, path):
                with outer.lock:
                    codes = outer.fail_next.get(path)
                    code = codes.pop(0) if codes else None
                if code is not None:
                    self._json(code, {"kind": "Status", "code": code})
                    return True
                return False

            def do_GET(self):
                path, _, query = self.path.partition("?")
                watching = "watch=true" in query
                if not watching and self._maybe_fail(path):
                    return
                if path == "/api/v1/nodes" and not watching:
                    with outer.lock:
                        items = list(outer.nodes.values())
                        rv = str(outer.rv)
                    self._json(200, {"items": items, "metadata": {"resourceVersion": rv}})
                elif path == "/api/v1/pods" and not watching:
                    with outer.lock:
                        items = list(outer.pods.values())
                        rv = str(outer.rv)
                    self._json(200, {"items": items, "metadata": {"resourceVersion": rv}})
                elif watching and path in ("/api/v1/nodes", "/api/v1/pods"):
                    kind = "nodes" if path.endswith("nodes") else "pods"
                    q = Queue()
                    with outer.lock:
                        outer.watchers.append((kind, q))
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    try:
                        while True:
                            event = q.get()
                            if event is None:
                                break
                            line = (json.dumps(event) + "\n").encode()
                            self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
                            self.wfile.flush()
                    except Exception:
                        pass
                elif path.startswith("/api/v1/nodes/"):
                    with outer.lock:
                        node = outer.nodes.get(path.split("/")[-1])
                    if node is None:
                        self._json(404, {"code": 404})
                    else:
                        self._json(200, node)
                elif path.startswith("/api/v1/namespaces/") and path.count("/") == 6:
                    ns, name = path.split("/")[4], path.split("/")[6]
                    with outer.lock:
                        pod = outer.pods.get(f"{ns}/{name}")
                    if pod is None:
                        self._json(404, {"code": 404})
                    else:
                        self._json(200, pod)
                else:
                    self._json(404, {"code": 404})

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length)) if length else {}
                if self._maybe_fail(self.path.partition("?")[0]):
                    return
                parts = self.path.split("/")
                if self.path.endswith("/binding"):
                    ns, name = parts[4], parts[6]
                    with outer.lock:
                        pod = outer.pods.get(f"{ns}/{name}")
                        if pod is None:
                            return self._json(404, {"code": 404})
                        pod.setdefault("spec", {})["nodeName"] = body["target"]["name"]
                        pod.setdefault("metadata", {}).setdefault("annotations", {}).update(
                            (body.get("metadata") or {}).get("annotations") or {}
                        )
                        outer.rv += 1
                        pod["metadata"]["resourceVersion"] = str(outer.rv)
                    outer.emit("pods", {"type": "MODIFIED", "object": pod})
                    self._json(201, {"kind": "Status", "status": "Success"})
                else:
                    self._json(404, {"code": 404})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self.thread.start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def emit(self, kind, event):
        with self.lock:
            for k, q in self.watchers:
                if k == kind:
                    q.put(event)

    def add_node(self, name):
        with self.lock:
            self.rv += 1
            node = {
                "metadata": {"name": name, "resourceVersion": str(self.rv)},
                "spec": {},
                "status": {"conditions": [{"type": "Ready", "status": "True"}]},
            }
            self.nodes[name] = node
        self.emit("nodes", {"type": "ADDED", "object": node})

    def add_pod(self, ns, name, annotations=None):
        with self.lock:
            self.rv += 1
            pod = {
                "metadata": {"name": name, "namespace": ns, "uid": name,
                             "annotations": annotations or {},
                             "resourceVersion": str(self.rv)},
                "spec": {"containers": []},
                "status": {"phase": "Pending"},
            }
            self.pods[f"{ns}/{name}"] = pod
        self.emit("pods", {"type": "ADDED", "object": pod})

    def close(self):
        with self.lock:
            for _, q in self.watchers:
                q.put(None)
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def apiserver():
    s = MiniApiServer()
    yield s
    s.close()


def wait_for(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_full_scheduler_stack_over_rest(apiserver):
    """The deployable configuration: HivedScheduler + webserver wired to a
    (mini) ApiServer through the REST client — filter decides, bind commits
    through the Bind subresource, the annotation lands on the pod."""
    import os

    from hivedscheduler_tpu.api import constants as C
    from hivedscheduler_tpu.api.config import load_config
    from hivedscheduler_tpu.common.utils import to_yaml
    from hivedscheduler_tpu.runtime import extender as ei
    from hivedscheduler_tpu.runtime.scheduler import HivedScheduler

    fixture = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "example", "config", "design", "tpu-hive.yaml")
    config = load_config(fixture)
    client = RestKubeClient(apiserver.url)
    scheduler = HivedScheduler(config, client)
    for n in sorted({n for ccl in scheduler.scheduler_algorithm.full_cell_list.values()
                     for c in ccl[max(ccl)] for n in c.nodes}):
        apiserver.add_node(n)
    spec = {"virtualCluster": "vc2", "priority": 0,
            "chipType": "v5e-chip", "chipNumber": 8}
    apiserver.add_pod("default", "job1", annotations={
        C.ANNOTATION_POD_SCHEDULING_SPEC: to_yaml(spec)})
    # make the pod hived-enabled (mini server stores raw dicts)
    with apiserver.lock:
        apiserver.pods["default/job1"]["spec"]["containers"] = [
            {"name": "c", "resources": {"limits": {
                C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1}}}]
    scheduler.start()  # recovery barrier: lists nodes + pods over REST

    pod = client.get_pod("default", "job1")
    result = scheduler.filter_routine(ei.ExtenderArgs(
        pod=pod, node_names=[n.name for n in client.list_nodes()]))
    assert result.node_names == ["v5e-host0/0-0"]
    scheduler.bind_routine(ei.ExtenderBindingArgs(
        pod_name="job1", pod_namespace="default", pod_uid="job1",
        node="v5e-host0/0-0"))
    bound = client.get_pod("default", "job1")
    assert bound.node_name == "v5e-host0/0-0"
    assert bound.annotations[C.ANNOTATION_POD_CHIP_ISOLATION] == "0,1,2,3,4,5,6,7"
    client.stop()


def test_list_watch_and_bind(apiserver):
    apiserver.add_node("n0")
    apiserver.add_pod("default", "pre-existing")

    client = RestKubeClient(apiserver.url)
    seen = {"nodes": [], "pods": [], "updates": []}
    client.on_node_event(
        lambda n: seen["nodes"].append(n.name), lambda o, n: None, lambda n: None
    )
    client.on_pod_event(
        lambda p: seen["pods"].append(p.key),
        lambda o, p: seen["updates"].append(p.key),
        lambda p: None,
    )
    client.sync()
    # list replayed as adds (the recovery barrier)
    assert seen["nodes"] == ["n0"] and seen["pods"] == ["default/pre-existing"]

    # watch delivers later objects (wait for both watches to connect: the
    # mini server has no resourceVersion replay, unlike a real ApiServer)
    assert wait_for(lambda: len(apiserver.watchers) == 2)
    apiserver.add_pod("default", "late")
    assert wait_for(lambda: "default/late" in seen["pods"])

    # reads
    assert client.get_node("n0") is not None
    assert client.get_node("ghost") is None
    assert client.get_pod("default", "late").name == "late"
    assert len(client.list_pods()) == 2

    # bind: node + annotations merged onto the pod, MODIFIED event flows back
    client.bind_pod(Binding(
        pod_name="late", pod_namespace="default", pod_uid="late",
        node="n0", annotations={"k": "v"},
    ))
    assert wait_for(lambda: "default/late" in seen["updates"])
    bound = client.get_pod("default", "late")
    assert bound.node_name == "n0" and bound.annotations["k"] == "v"
    client.stop()


def test_bearer_token_sent(apiserver):
    """An explicit bearer token must ride every request's Authorization
    header (list, get, and the bind write all share _headers)."""
    import urllib.request

    seen_auth = []

    class Recorder(urllib.request.BaseHandler):
        def http_request(self, req):
            seen_auth.append(req.get_header("Authorization"))
            return req

    opener = urllib.request.build_opener(Recorder())
    old_opener = urllib.request._opener
    urllib.request.install_opener(opener)
    try:
        client = RestKubeClient(apiserver.url, bearer_token="sekret")
        apiserver.add_node("n0")
        apiserver.add_pod("default", "p1")
        client.list_nodes()
        client.get_node("n0")
        client.bind_pod(Binding(pod_name="p1", pod_namespace="default",
                                pod_uid="p1", node="n0"))
    finally:
        urllib.request.install_opener(old_opener)
    assert len(seen_auth) >= 3
    assert all(a == "Bearer sekret" for a in seen_auth)


def test_bearer_token_refused_over_plaintext_offhost():
    """ADVICE r1: an explicit bearer token must not ride plaintext HTTP to a
    non-loopback address — construction refuses (loopback is allowed, with a
    warning, for kubectl proxy / test fakes)."""
    import pytest

    with pytest.raises(ValueError, match="plaintext"):
        RestKubeClient("http://apiserver.example:8080", bearer_token="sekret")
    # https off-host is fine
    RestKubeClient("https://apiserver.example:6443", bearer_token="sekret")
