"""Big-topology placement goldens: v5p-1024 (8x8x16 ICI mesh, 4-chip hosts).

The gnarly-fixture goldens (test_adversarial_goldens.py) pin behavior on
small chains; regressions in mesh-tiling order, buddy tie-breaking or packing
only visible at pod scale would slip through them. These goldens pin exact
node placements, the buddy free-list level ladder, and sub-mesh contiguity
for a deterministic sequence on the benchmark topology (mirroring the
reference's determinism strategy, ``hived_algorithm_test.go:566-608``, at
the scale of ``BASELINE.json``'s driver metric).

Chain levels: chip(1), 2x2x1 host(2), 2x2x2(3), 4x2x2(4), 4x4x2(5),
4x4x4(6), 8x4x4(7), 8x8x4(8), 8x8x8(9), 8x8x16 top(10).
"""

import logging

import pytest

from helpers import make_pod

from hivedscheduler_tpu.api.config import Config, new_config
from hivedscheduler_tpu.api.types import (
    CellTypeSpec,
    MeshLevelSpec,
    MeshSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    VirtualCellSpec,
    VirtualClusterSpec,
)
from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.k8s.types import Node
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

logging.getLogger().setLevel(logging.ERROR)

LEVELS = [
    ("v5p-2x2x2", (2, 2, 2)),
    ("v5p-4x2x2", (4, 2, 2)),
    ("v5p-4x4x2", (4, 4, 2)),
    ("v5p-4x4x4", (4, 4, 4)),
    ("v5p-8x4x4", (8, 4, 4)),
    ("v5p-8x8x4", (8, 8, 4)),
    ("v5p-8x8x8", (8, 8, 8)),
]


def build_config():
    mesh = MeshSpec(
        topology=(8, 8, 16),
        chip_type="v5p-chip",
        host_shape=(2, 2, 1),
        levels=[MeshLevelSpec(name=n, shape=s) for n, s in LEVELS],
    )
    return new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={"v5p-1024": CellTypeSpec(mesh=mesh)},
            physical_cells=[
                PhysicalCellSpec(cell_type="v5p-1024", cell_address="pod0")
            ],
        ),
        virtual_clusters={
            "vc-a": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=2, cell_type="v5p-1024.v5p-8x8x4")
            ]),
            "vc-b": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=4, cell_type="v5p-1024.v5p-4x4x4")
            ]),
        },
    ))


def fresh_algo():
    h = HivedAlgorithm(build_config())
    for n in sorted({n for ccl in h.full_cell_list.values()
                     for c in ccl[max(ccl)] for n in c.nodes}):
        h.add_node(Node(name=n))
    return h


@pytest.fixture
def algo():
    return fresh_algo()


def nodes_of(h):
    return sorted({n for ccl in h.full_cell_list.values()
                   for c in ccl[max(ccl)] for n in c.nodes})


def gang(h, vc, group, pods, chips, prio=10):
    """Schedule + allocate a full gang; returns (bound_pods, placements)."""
    nodes = nodes_of(h)
    bound, placements = [], []
    for i in range(pods):
        spec = {"virtualCluster": vc, "priority": prio,
                "leafCellType": "v5p-chip", "leafCellNumber": chips,
                "affinityGroup": {"name": group, "members": [
                    {"podNumber": pods, "leafCellNumber": chips}]}}
        pod = make_pod(f"{group}-{i}", spec)
        r = h.schedule(pod, nodes, FILTERING_PHASE)
        assert r.pod_bind_info is not None, (i, r.pod_wait_info)
        placements.append(
            (r.pod_bind_info.node, tuple(r.pod_bind_info.leaf_cell_isolation))
        )
        bp = new_binding_pod(pod, r.pod_bind_info)
        h.add_allocated_pod(bp)
        bound.append(bp)
    return bound, placements


def host_origin(node):
    # mesh node names are "pod0/x-y-z" with the host's origin coordinates
    return tuple(int(v) for v in node.split("/")[1].split("-"))


def free_level_counts(h):
    ccl = h.free_cell_list["v5p-1024"]
    return {lv: len(ccl[lv]) for lv in sorted(ccl) if len(ccl[lv])}


class TestScaleGoldens:
    def test_256chip_gang_tiling_golden(self, algo):
        """The first 256-chip gang (64 pods x 4) fills the origin 8x8x4
        corner in buddy-recursive tiling order; full delete restores the
        pristine free list."""
        assert free_level_counts(algo) == {10: 1}  # one free 8x8x16 cell
        bound, placements = gang(algo, "vc-a", "scale-g0", 64, 4)
        origins = [host_origin(n) for n, _ in placements]
        assert len(set(origins)) == 64
        # contiguity at type level: the whole gang inside one 8x8x4 corner
        assert all(x < 8 and y < 8 and z < 4 for x, y, z in origins)
        # full-host chip isolation, every pod
        assert all(iso == (0, 1, 2, 3) for _, iso in placements)
        # tiling-order golden: buddy recursion visits the 2x2x2 twin (z+1),
        # then the x buddy, then y — any tie-break change diffs here
        assert origins[:8] == [
            (0, 0, 0), (0, 0, 1), (2, 0, 0), (2, 0, 1),
            (0, 2, 0), (0, 2, 1), (2, 2, 0), (2, 2, 1),
        ], origins[:8]
        for bp in bound:
            algo.delete_allocated_pod(bp)
        assert free_level_counts(algo) == {10: 1}

    def test_tiling_order_is_deterministic_across_rebuilds(self, algo):
        """Two fresh schedulers must place the same gang identically —
        set/dict iteration order must not leak into placement."""
        _, p1 = gang(algo, "vc-a", "scale-det", 64, 4)
        _, p2 = gang(fresh_algo(), "vc-a", "scale-det", 64, 4)
        assert p1 == p2

    def test_buddy_split_level_ladder_golden(self, algo):
        """A single 4-chip pod in vc-b (preassigned level 6, the 4x4x4 cube)
        splits the top cell down to its preassigned level only: one free
        buddy each at levels 6..9 — allocation below the preassigned cell is
        VC-internal and must NOT appear in the physical free list."""
        bound, placements = gang(algo, "vc-b", "scale-split", 1, 4)
        assert free_level_counts(algo) == {6: 1, 7: 1, 8: 1, 9: 1}
        assert placements == [("pod0/0-0-0", (0, 1, 2, 3))]
        for bp in bound:
            algo.delete_allocated_pod(bp)
        assert free_level_counts(algo) == {10: 1}

    def test_two_vc_gangs_do_not_fragment(self, algo):
        """vc-a's 256-chip gang and 4 x vc-b 64-chip gangs coexist without
        fragmentation: each 64-chip gang lands on a contiguous 4x4x4 cube
        (16 hosts of shape 2x2x1 => coordinate spans (2,2,3)), packed into
        the four corners of the z>=4 half left free by vc-a."""
        bound_a, _ = gang(algo, "vc-a", "scale-a", 64, 4)
        all_bound = [bound_a]
        expected_corners = [(0, 0, 4), (4, 0, 4), (0, 4, 4), (4, 4, 4)]
        for g in range(4):
            bound_b, placements = gang(algo, "vc-b", f"scale-b{g}", 16, 4)
            all_bound.append(bound_b)
            origins = [host_origin(n) for n, _ in placements]
            xs, ys, zs = zip(*origins)
            spans = (max(xs) - min(xs), max(ys) - min(ys), max(zs) - min(zs))
            assert len(set(origins)) == 16
            assert spans == (2, 2, 3), (g, spans, sorted(origins))
            assert min(origins) == expected_corners[g], (g, min(origins))
        for bound in all_bound:
            for bp in bound:
                algo.delete_allocated_pod(bp)
        assert free_level_counts(algo) == {10: 1}
