"""The workload fault ladder (ISSUE 3 acceptance):

- in-process divergence ladder: NaN halt / rollback / skip (--on-nan),
  spike guard, supervisor CLI-flag reachability (CLAUDE.md blind spot:
  features unreachable from the train CLI have slipped twice);
- subprocess soaks (slow-marked): SIGTERM-at-step-k checkpoint-and-exit,
  kill -9 -> bit-exact resume, watchdog fires on an injected hang — all
  through chaos.workload's seeded harness with the CLAUDE.md CPU-only env
  recipe (a killable child must never hold the TPU tunnel)."""

import json
import re

import pytest

pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")

from hivedscheduler_tpu.parallel import supervisor as sup_lib

MODEL = ["--batch", "8", "--seq-len", "16", "--vocab-size", "64",
         "--d-model", "16", "--n-layers", "1", "--n-heads", "2",
         "--d-ff", "32", "--log-every", "100"]


def run_train(args):
    from hivedscheduler_tpu import train

    return train.main(MODEL + args)


def timeline_records(path):
    return [json.loads(l) for l in open(path) if l.strip()]


def last_loss_per_step(path):
    out = {}
    for rec in timeline_records(path):
        out[rec["step"]] = rec["loss"]
    return out


def metric_value(name):
    from hivedscheduler_tpu.runtime.metrics import REGISTRY

    m = re.search(rf"^{name} (\d+)", REGISTRY.render(), re.M)
    return int(m.group(1)) if m else 0


class TestDivergenceLadder:
    def test_on_nan_halt_exits_nonzero_with_last_good_checkpoint(
            self, tmp_path, monkeypatch):
        from hivedscheduler_tpu.parallel import checkpoint as ckpt

        monkeypatch.setenv(sup_lib.ENV_FAULT_NAN_AT, "4")
        ck, tl = str(tmp_path / "ck"), str(tmp_path / "tl.jsonl")
        rc = run_train(["--steps", "6", "--checkpoint-dir", ck,
                        "--checkpoint-every", "2", "--timeline", tl,
                        "--on-nan", "halt"])
        assert rc == sup_lib.EXIT_DIVERGED
        # the poisoned step was never committed: newest checkpoint predates it
        assert ckpt.latest_step(ck) == 2
        losses = last_loss_per_step(tl)
        assert losses[4] != losses[4]  # NaN recorded at the diverged step

    def test_on_nan_rollback_recovers_and_completes(self, tmp_path,
                                                    monkeypatch):
        import math

        monkeypatch.setenv(sup_lib.ENV_FAULT_NAN_AT, "4")
        ck, tl = str(tmp_path / "ck"), str(tmp_path / "tl.jsonl")
        rollbacks0 = metric_value("tpu_hive_train_rollbacks_total")
        rc = run_train(["--steps", "6", "--checkpoint-dir", ck,
                        "--checkpoint-every", "2", "--timeline", tl,
                        "--on-nan", "rollback"])
        assert rc == 0
        assert metric_value("tpu_hive_train_rollbacks_total") == rollbacks0 + 1
        recs = timeline_records(tl)
        # the diverged step was recorded (NaN), then replayed clean after
        # the rollback — the LAST record of every step is finite and the
        # run reached --steps
        assert any(r["step"] == 4 and r["loss"] != r["loss"] for r in recs)
        final = last_loss_per_step(tl)
        assert set(final) == set(range(1, 7))
        assert all(math.isfinite(v) for v in final.values())

    def test_rollback_budget_exhaustion_halts(self, tmp_path, monkeypatch):
        """--max-rollbacks 0: the first divergence already exceeds the
        budget — the run must halt, not livelock restoring."""
        monkeypatch.setenv(sup_lib.ENV_FAULT_NAN_AT, "4")
        ck = str(tmp_path / "ck")
        rc = run_train(["--steps", "6", "--checkpoint-dir", ck,
                        "--checkpoint-every", "2", "--on-nan", "rollback",
                        "--max-rollbacks", "0"])
        assert rc == sup_lib.EXIT_DIVERGED

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_skip_nonfinite_gate_passes_state_through(self):
        """--on-nan skip compiles the update gate into the jitted step: a
        non-finite loss must leave params AND opt_state (including the
        optimizer step count) bit-identical to the inputs."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from hivedscheduler_tpu.models import transformer as tm
        from hivedscheduler_tpu.parallel import topology
        from hivedscheduler_tpu.parallel.train import make_sharded_train_step

        cfg = tm.TransformerConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            max_seq_len=16, dtype=jnp.float32,
        )
        mesh = topology.make_mesh(topology.MeshAxes(dp=1),
                                  topology.get_devices(1))
        step_fn, init_fn, tok_sh = make_sharded_train_step(
            cfg, mesh, skip_nonfinite=True)
        params, opt = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64),
            tok_sh)
        # healthy step: the gate must NOT block real updates
        p0_host = jax.device_get(params)
        p1, o1, loss1 = step_fn(params, opt, tokens)
        assert bool(jnp.isfinite(loss1))
        changed = any(
            not np.array_equal(a, b) for a, b in
            zip(jax.tree.leaves(p0_host), jax.tree.leaves(jax.device_get(p1)))
        )
        assert changed, "gate swallowed a healthy update"
        # poisoned state -> non-finite loss -> pass-through
        bad = jax.tree.map(lambda x: x * float("nan"), p1)
        bad_host = jax.device_get(bad)
        o1_host = jax.device_get(o1)
        p2, o2, loss2 = step_fn(bad, o1, tokens)
        assert not bool(jnp.isfinite(loss2))
        for a, b in zip(jax.tree.leaves(bad_host),
                        jax.tree.leaves(jax.device_get(p2))):
            np.testing.assert_array_equal(a, b)  # NaN == NaN bitwise here
        for a, b in zip(jax.tree.leaves(o1_host),
                        jax.tree.leaves(jax.device_get(o2))):
            np.testing.assert_array_equal(a, b)  # incl. the step count

    def test_spike_factor_triggers_halt(self, tmp_path):
        """A finite but exploding loss trips the spike guard: warm up on a
        tiny LR... simplest deterministic trigger is a spike factor below 1
        (any loss 'spikes' past warmup)."""
        rc = run_train(["--steps", "8", "--on-nan", "halt",
                        "--loss-spike-factor", "0.0001"])
        assert rc == sup_lib.EXIT_DIVERGED


class TestSupervisorCliReachability:
    def test_all_supervisor_flags_reachable(self, tmp_path):
        """Every supervisor knob must be drivable from the CLI in one
        normal completing run (CLAUDE.md recurring blind spot)."""
        ck = str(tmp_path / "ck")
        rc = run_train([
            "--steps", "2", "--checkpoint-dir", ck,
            "--checkpoint-every", "10",
            "--watchdog-secs", "60", "--grace-secs", "5",
            "--on-nan", "skip", "--loss-spike-factor", "1000",
            "--max-rollbacks", "1", "--data-seed", "7",
        ])
        assert rc == 0

    def test_on_nan_skip_rejected_with_lora(self):
        with pytest.raises(SystemExit):
            run_train(["--steps", "1", "--lora-rank", "2",
                       "--on-nan", "skip"])

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_resume_records_loader_state_and_counts(self, tmp_path):
        """A resumed incarnation bumps tpu_hive_train_resumes_total and the
        commit marker carries the canonical loader state."""
        from hivedscheduler_tpu.parallel import checkpoint as ckpt
        from hivedscheduler_tpu.parallel.data import LoaderState

        ck = str(tmp_path / "ck")
        assert run_train(["--steps", "2", "--checkpoint-dir", ck,
                          "--checkpoint-every", "2"]) == 0
        meta = ckpt.read_metadata(ck)
        state = LoaderState.from_dict(meta["loader"])  # canonical fields
        assert state.step == 2 and state.bitgen is not None
        resumes0 = metric_value("tpu_hive_train_resumes_total")
        assert run_train(["--steps", "4", "--checkpoint-dir", ck,
                          "--checkpoint-every", "2"]) == 0
        assert metric_value("tpu_hive_train_resumes_total") == resumes0 + 1
        assert ckpt.read_metadata(ck)["loader"]["step"] == 4


@pytest.mark.slow
class TestWorkloadSoak:
    """Subprocess fault ladder — each soak runs a reference + faulted +
    final incarnation of the real train CLI (CPU-only env recipe)."""

    def _soak(self, tmp_path, kinds):
        from hivedscheduler_tpu.chaos.workload import (
            WorkloadChaosHarness,
            WorkloadFaultPlan,
        )

        harness = WorkloadChaosHarness(
            seed=42, workdir=str(tmp_path),
            plan=WorkloadFaultPlan(episodes=1, kinds=kinds))
        report = harness.run()
        assert report["violations"] == [], report
        # the goodput audit rode along: conservation, the rework replay
        # and torn-incarnation bookkeeping are all inside run() — here we
        # only pin that the block is populated (ISSUE 16 acceptance:
        # conservation asserted in EVERY workload chaos episode)
        gp = report["goodput"]
        assert gp["incarnations"] == 2
        assert gp["steps"] >= harness.steps
        assert set(gp["phases"]) and gp["goodput_fraction"] is not None
        return report

    def test_sigterm_checkpoints_and_exits_cleanly(self, tmp_path):
        report = self._soak(tmp_path, ("sigterm",))
        # the cooperative preemption's checkpoint time was attributed
        assert report["goodput"]["phases"]["checkpoint_save"] > 0.0
        assert report["goodput"]["torn"] == 0

    def test_kill9_resume_is_bit_exact(self, tmp_path):
        report = self._soak(tmp_path, ("sigkill",))
        # the killed incarnation never reached its atexit summary
        assert report["goodput"]["torn"] == 1

    def test_watchdog_fires_on_injected_hang(self, tmp_path):
        # the watchdog's os._exit also skips the summary: torn, not lost
        report = self._soak(tmp_path, ("hang",))
        assert report["goodput"]["torn"] == 1
