"""Recovery at scale: the work-preserving reconfiguration golden on the
benchmark v5p-1024 topology. Hundreds of allocated pods must replay through
the runtime's recovery barrier (runtime/scheduler.py start()) with every
gang's physical placement preserved verbatim — compared at CHIP granularity
(node -> exact leaf-cell indices), so a restart that lands a gang on the
same nodes but different chips (broken ICI contiguity) counts as lost — in
bounded time (reference behavior: hived_algorithm_test.go:1042-1092, tested
there at toy scale)."""

import pytest

import bench


@pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
def test_recovery_barrier_at_v5p1024_scale():
    rec_ms, n_pods, n_groups, preserved_pct = bench.run_recovery()
    # the random gang mix packs the full 1024-chip pod (256 x 4-chip pods)
    assert n_pods >= 200, (n_pods, n_groups)
    assert n_groups >= 10
    assert preserved_pct == 100.0
    # ~40 ms on the reference runner; generous CI headroom
    assert rec_ms < 10_000.0, rec_ms
