"""Test session config.

JAX tests run on a virtual 8-device CPU mesh (multi-chip TPU hardware is not
available in CI): the env vars MUST be set before jax is first imported, so
this conftest sets them at collection time and never imports jax itself.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
