"""Test session config.

JAX tests run on a virtual 8-device CPU mesh (multi-chip TPU hardware is not
available in CI): the env vars are set at collection time, before any test
imports jax.

The axon TPU environment's sitecustomize exports ``JAX_PLATFORMS=axon`` /
``PALLAS_AXON_POOL_IPS`` AND pre-imports jax at interpreter startup (its
.pth hook registers the PJRT plugin), so by the time this conftest runs the
``jax_platforms`` config default is already baked to ``"axon,cpu"`` — a
bare ``pytest tests/`` would then contend for the single-grant TPU tunnel
at the first ``jax.devices()`` (and can wedge it if killed mid-op). The
test suite never needs the TPU, so this defuses both layers: the env vars
(for subprocesses spawned by tests) and, when jax is already imported, the
live config. Set ``HIVED_TEST_TPU=1`` to deliberately run tests against
the real backend.
"""

import os
import sys

if os.environ.get("HIVED_TEST_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
if os.environ.get("HIVED_TEST_TPU") != "1" and "jax" in sys.modules:
    # too late for the env var: sitecustomize already imported jax with the
    # axon default, so override the live config (backends init lazily — no
    # backend has been touched yet at collection time)
    import jax

    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs with -m 'not slow'; long soaks opt out via this marker
    config.addinivalue_line(
        "markers", "slow: long-running soak/stress variants excluded from tier-1"
    )


@pytest.fixture(scope="session", autouse=True)
def _no_tpu_tunnel():
    """Guard: without the HIVED_TEST_TPU opt-in, no test process may reach
    the axon TPU backend (single-grant tunnel; see module docstring).

    Checked at session END, and only when some test actually imported jax:
    probing eagerly would itself force a backend init (and, if the override
    were ever broken, would be the very thing that grabs the tunnel)."""
    yield
    if os.environ.get("HIVED_TEST_TPU") != "1" and "jax" in sys.modules:
        import jax

        backends = getattr(jax._src.xla_bridge, "_backends", {})
        touched = set(backends) - {"cpu"}
        assert not touched, (
            f"test session initialized non-cpu backend(s) {sorted(touched)} "
            "without HIVED_TEST_TPU=1 — the conftest override failed"
        )
