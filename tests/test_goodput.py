"""Workload goodput ledger (ISSUE 16, doc/design/observability.md): the
step-phase taxonomy is a registry (OBS003), Σ phase-seconds == process
wallclock is the conservation invariant, rework classification replays
exactly across incarnations through the shared spool, and the
scheduler-side busy interval must cover the workload-observed seconds
(the capacity-ledger bridge). All fake-clock — no jax, no subprocesses
(the subprocess form rides the slow chaos soaks)."""

import json
import os

import pytest

from hivedscheduler_tpu.obs import goodput


def _ledger():
    led = goodput.GoodputLedger(metrics=False)
    led.enabled = True
    return led


# ---------------------------------------------------------------------------
# registry + conservation
# ---------------------------------------------------------------------------

def test_unregistered_phase_raises_obs003():
    led = _ledger()
    led.start(at=0.0)
    with pytest.raises(ValueError, match="not a registered step phase"):
        led.phase("made_up_phase", at=1.0)


def test_conservation_exact_under_fake_clock():
    led = _ledger()
    led.start(at=0.0)                      # init
    led.phase("compile", at=1.5)
    led.phase("step_compute", at=4.0)
    led.phase("data_wait", at=7.0)
    led.phase("step_compute", at=7.25)
    totals = led.totals(at=10.0)
    assert totals == {"init": 1.5, "compile": 2.5, "step_compute": 5.75,
                      "data_wait": 0.25}
    assert led.wallclock(at=10.0) == 10.0
    assert led.conservation_gap(at=10.0) == 0.0
    assert led.goodput_fraction(at=10.0) == 5.75 / 10.0


def test_same_phase_is_noop_and_exactly_one_open():
    led = _ledger()
    led.start(at=0.0)
    led.phase("step_compute", at=1.0)
    led.phase("step_compute", at=2.0)      # no-op: interval continues
    assert led.current_phase() == "step_compute"
    assert led.totals(at=3.0) == {"init": 1.0, "step_compute": 2.0}


def test_close_freezes_wallclock_and_is_idempotent():
    led = _ledger()
    led.start(at=0.0)
    led.phase("step_compute", at=1.0)
    led.close(at=5.0)
    led.close(at=99.0)                     # idempotent
    assert led.wallclock(at=50.0) == 5.0   # frozen at close
    assert led.conservation_gap(at=50.0) == 0.0
    assert led.current_phase() is None


def test_span_restores_surrounding_phase():
    led = _ledger()
    led.start(at=0.0)
    led.phase("step_compute", at=1.0)
    with led.span("checkpoint_save", at=2.0):
        assert led.current_phase() == "checkpoint_save"
    # the span exit restores the surrounding phase at the REAL clock (the
    # runtime contract), so assert conservation at real-now, not fake time
    assert led.current_phase() == "step_compute"
    assert led.totals()["checkpoint_save"] > 0.0
    assert abs(led.conservation_gap()) < 1e-6


def test_disabled_is_inert():
    led = goodput.GoodputLedger(metrics=False)  # enabled=False
    led.start(at=0.0)
    led.phase("step_compute", at=1.0)
    led.note_step(1, at=2.0)
    assert led.totals(at=3.0) == {}
    assert led.wallclock(at=3.0) == 0.0
    assert led.goodput_fraction(at=3.0) is None
    # span on a disabled ledger is the shared no-op context manager
    with led.span("drain", at=1.0):
        pass
    assert led.current_phase() is None


def test_snapshot_keys_cover_registry():
    led = _ledger()
    led.start(at=0.0)
    led.note_step(1, is_compile=True, at=1.0)
    led.note_step_done(1, at=2.0)
    snap = led.snapshot(at=3.0)
    assert set(snap["phases"]) == set(goodput.STEP_PHASES)
    assert snap["conservationGapS"] == 0.0
    assert snap["steps"] == 1 and snap["maxStep"] == 1
    assert snap["enabled"] is True


# ---------------------------------------------------------------------------
# rework classification + the cross-incarnation spool
# ---------------------------------------------------------------------------

def test_note_step_classifies_rework_over_compile():
    # a resumed incarnation's first step is both its compile step and a
    # re-trained step: ALL of it is fault-caused badput, so rework wins
    led = _ledger()
    led.seed_max_step(3)
    led.start(at=0.0)
    led.note_step(3, is_compile=True, at=1.0)
    assert led.current_phase() == "rework"
    led.note_step_done(3, at=2.0)
    led.note_step(4, at=2.0)
    assert led.current_phase() == "step_compute"
    led.note_step_done(4, at=3.0)
    snap = led.snapshot(at=3.0)
    assert snap["reworkSteps"] == 1 and snap["maxStep"] == 4


def test_spool_round_trip_and_cross_incarnation_replay(tmp_path):
    sp = str(tmp_path / "goodput.jsonl")
    led = _ledger()
    led.open_spool(sp)
    led.start(at=0.0)
    led.note_step(1, is_compile=True, at=1.0)
    led.note_step_done(1, at=2.0)
    led.note_step(2, at=2.0)
    led.note_step_done(2, at=3.0)
    led.close(at=4.0)

    # incarnation 2 resumes from the step-1 checkpoint: step 2 is rework
    led2 = _ledger()
    led2.seed_max_step(goodput.spool_max_step(sp))
    led2.open_spool(sp)
    led2.start(at=10.0)
    led2.note_step(2, is_compile=True, at=11.0)
    assert led2.current_phase() == "rework"
    led2.note_step_done(2, at=12.0)
    led2.note_step(3, at=12.0)
    led2.note_step_done(3, at=13.0)
    led2.close(at=14.0)

    records = goodput.read_spool(sp)
    assert goodput.check_spool(sp) == []
    assert goodput.check_rework_classification(records) == []
    agg = goodput.aggregate_spool(records)
    assert agg["incarnations"] == 2 and agg["torn"] == 0
    assert agg["steps"] == 4 and agg["rework_steps"] == 1
    assert agg["summarized_wallclock_s"] == 8.0


def test_torn_incarnation_counted_and_steps_still_attributed(tmp_path):
    sp = str(tmp_path / "goodput.jsonl")
    led = _ledger()
    led.open_spool(sp)
    led.start(at=0.0)
    led.note_step(1, at=1.0)
    led.note_step_done(1, at=2.0)
    # no close(): the kill -9 shape — start + step records, no summary
    agg = goodput.aggregate_spool(goodput.read_spool(sp))
    assert agg["torn"] == 1 and agg["incarnations"] == 1
    assert agg["steps"] == 1


def test_check_rework_classification_flags_drift():
    recs = [
        {"kind": "step", "pid": 1, "step": 1, "rework": False},
        {"kind": "step", "pid": 1, "step": 2, "rework": False},
        {"kind": "step", "pid": 2, "step": 2, "rework": False},  # drifted
    ]
    got = goodput.check_rework_classification(recs)
    assert len(got) == 1 and "misclassified" in got[0]


def test_check_spool_flags_conservation_and_registry(tmp_path):
    sp = str(tmp_path / "bad.jsonl")
    with open(sp, "w") as f:
        f.write(json.dumps({"kind": "start", "pid": 1, "t0": 0.0,
                            "phase": "init"}) + "\n")
        f.write(json.dumps({"kind": "phase", "pid": 1,
                            "phase": "rogue_phase",
                            "start": 0.0, "end": 1.0}) + "\n")
        f.write(json.dumps({"kind": "summary", "pid": 1,
                            "wallclock_s": 5.0,
                            "phases": {"init": 1.0},  # gap: 1.0 != 5.0
                            "steps": 0, "rework_steps": 0,
                            "max_step": 0}) + "\n")
        f.write("{torn trailing li")  # tolerated, never a violation
    got = goodput.check_spool(sp)
    assert any("rogue_phase" in v for v in got)
    assert any("wallclock" in v for v in got)


def test_dead_spool_does_not_fail_emit(tmp_path):
    led = _ledger()
    led.open_spool(str(tmp_path / "sp.jsonl"))
    led._spool.close()  # yank the file out from under the ledger
    led.start(at=0.0)   # must not raise; spool degrades to None
    led.note_step(1, at=1.0)
    assert led._spool is None


# ---------------------------------------------------------------------------
# the capacity-ledger bridge
# ---------------------------------------------------------------------------

def test_reconcile_busy_contract():
    assert goodput.reconcile_busy(10.0, 9.0, slack_s=5.0) is None
    # workload observed MORE than the scheduler billed: accounting bug
    neg = goodput.reconcile_busy(7.0, 8.0, slack_s=5.0)
    assert neg is not None and "covered" in neg
    # busy exceeds observed beyond slack: unattributed busy time
    over = goodput.reconcile_busy(20.0, 8.0, slack_s=5.0)
    assert over is not None and "slack" in over


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_chrome_events_single_named_lane():
    led = _ledger()
    led.start(at=0.0)
    led.phase("compile", at=1.0)
    led.phase("step_compute", at=2.0)
    events = led.chrome_events(t0=0.0)
    meta = [e for e in events if e["ph"] == "M"]
    spans = [e for e in events if e["ph"] == "X"]
    assert len(meta) == 1
    assert meta[0]["args"]["name"] == "workload goodput"
    assert {e["tid"] for e in events} == {goodput._LANE_TID}
    names = [e["name"] for e in spans]
    assert names[:2] == ["phase:init", "phase:compile"]
    assert all(e["cat"] == "goodput" for e in spans)


def test_trace_merge_carries_goodput_lane():
    from hivedscheduler_tpu.obs import trace

    goodput.GOODPUT.clear()
    goodput.GOODPUT.enabled = True
    try:
        goodput.GOODPUT.start()
        goodput.GOODPUT.phase("step_compute")
        out = trace.to_chrome_trace()
        names = {e.get("name") for e in out["traceEvents"]}
        assert any(str(n).startswith("phase:") for n in names)
    finally:
        goodput.GOODPUT.enabled = False
        goodput.GOODPUT.clear()


def test_module_enable_spools_and_seeds(tmp_path):
    sp = str(tmp_path / "spool.jsonl")
    with open(sp, "w") as f:
        f.write(json.dumps({"kind": "step", "pid": 9, "step": 7,
                            "rework": False}) + "\n")
    try:
        goodput.enable(spool_path=sp)
        assert goodput.enabled()
        # the prior incarnation's high-water mark was replayed from the
        # shared spool, so a re-trained step classifies as rework
        goodput.note_step(7)
        assert goodput.GOODPUT.current_phase() == "rework"
        goodput.GOODPUT.close()
        agg = goodput.aggregate_spool(goodput.read_spool(sp))
        assert agg["incarnations"] == 1  # only OUR start record
        assert agg["summaries"][0]["max_step"] == 7
    finally:
        goodput.disable()
        goodput.GOODPUT.clear()


def test_envflag_registered():
    from hivedscheduler_tpu.common import envflags

    assert "HIVED_GOODPUT" in envflags.REGISTRY
