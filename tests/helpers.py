"""Shared test helpers: pod construction, cluster bootstrap, status walking."""

from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.common.utils import to_json, to_yaml
from hivedscheduler_tpu.k8s.types import Container, Node, Pod

V5E32_CELL_TYPES = {
    "v5e-32": {"mesh": {
        "topology": [4, 8], "chipType": "v5e-chip", "hostShape": [2, 4],
        "levels": [{"name": "v5e-16", "shape": [4, 4]}]}},
}


def make_pod(name, spec_dict, uid=None, yaml_spec=False):
    """A hived-enabled pod with the scheduling-spec annotation (JSON by
    default — valid YAML; pass yaml_spec=True to simulate a human-written
    annotation)."""
    serialize = to_yaml if yaml_spec else to_json
    return Pod(
        name=name,
        uid=uid or name,
        annotations={C.ANNOTATION_POD_SCHEDULING_SPEC: serialize(spec_dict)},
        containers=[Container(resource_limits={C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})],
    )


def all_node_names(algo):
    return sorted({
        n for ccl in algo.full_cell_list.values()
        for c in ccl[max(ccl)] for n in c.nodes
    })


def set_healthy_nodes(algo):
    """Mark every configured node healthy; returns the node names."""
    nodes = all_node_names(algo)
    for n in nodes:
        algo.add_node(Node(name=n))
    return nodes


def walk_status(statuses):
    """Depth-first over inspect cell statuses (physical or virtual)."""
    for s in statuses:
        yield s
        yield from walk_status(s.cell_children)


def validate_chrome_trace(obj):
    """Assert ``obj`` is a valid Chrome trace (JSON Object Format) that
    Perfetto / chrome://tracing load: a traceEvents array of event objects
    each carrying name/ph/pid/tid and a numeric ts; complete ("X") events
    additionally need a non-negative numeric dur. Returns the events."""
    assert isinstance(obj, dict), "trace must be the JSON object format"
    events = obj.get("traceEvents")
    assert isinstance(events, list), "traceEvents must be an array"
    for ev in events:
        assert isinstance(ev, dict)
        assert isinstance(ev.get("name"), str) and ev["name"]
        assert isinstance(ev.get("ph"), str) and ev["ph"]
        assert isinstance(ev.get("ts"), (int, float))
        assert isinstance(ev.get("pid"), int)
        assert isinstance(ev.get("tid"), int)
        if ev["ph"] == "X":
            assert isinstance(ev.get("dur"), (int, float)) and ev["dur"] >= 0
        if "args" in ev:
            assert isinstance(ev["args"], dict)
    return events
