"""Held-out evaluation CLI (hivedscheduler_tpu.eval).

Pins the triad contract: a checkpoint trained on a structured corpus must
evaluate strictly better than random init on that corpus, sequential
windows make two runs bit-identical, and MoE training regularizers stay
out of the reported loss (perplexity must be exp(pure LM CE))."""

import numpy as np
import pytest

pytest.importorskip("jax")


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    # a strongly learnable corpus: a repeating 8-token motif
    path = tmp_path_factory.mktemp("eval") / "corpus.bin"
    motif = np.array([3, 17, 29, 5, 40, 11, 60, 23], dtype=np.uint16)
    np.tile(motif, 4096).tofile(path)
    return str(path)


MODEL = ["--vocab-size", "64", "--d-model", "32", "--n-layers", "2",
         "--n-heads", "4", "--d-ff", "64", "--seq-len", "32",
         "--batch", "2", "--tp", "2", "--sp", "2"]  # dp=2 on the 8-CPU mesh


def run_eval(args, capsys):
    from hivedscheduler_tpu import eval as ev

    assert ev.main(args) == 0
    line = [l for l in capsys.readouterr().out.splitlines() if "loss" in l][-1]
    return float(line.split()[1]), float(line.split()[3])


@pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
def test_trained_checkpoint_beats_random_init(tmp_path, corpus, capsys):
    from hivedscheduler_tpu import train

    ckpt = str(tmp_path / "ckpt")
    assert train.main(MODEL + ["--steps", "25", "--data", corpus,
                               "--checkpoint-dir", ckpt,
                               "--checkpoint-every", "100",
                               "--log-every", "100"]) in (0, None)

    eval_args = MODEL + ["--data", corpus, "--max-steps", "6"]
    rand_loss, rand_ppl = run_eval(eval_args, capsys)
    loss, ppl = run_eval(eval_args + ["--checkpoint-dir", ckpt], capsys)
    assert loss < rand_loss - 0.5, (loss, rand_loss)
    assert ppl == pytest.approx(np.exp(loss), rel=1e-4)

    # sequential windows: re-running is bit-identical
    loss2, _ = run_eval(eval_args + ["--checkpoint-dir", ckpt], capsys)
    assert loss2 == loss


def test_eval_excludes_moe_regularizers():
    import jax

    from hivedscheduler_tpu.models import transformer as tm
    from hivedscheduler_tpu.parallel import topology
    from hivedscheduler_tpu.parallel.train import (
        loss_fn,
        make_sharded_eval_step,
    )

    cfg = tm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, n_experts=2, moe_aux_weight=0.5,
    )
    axes = topology.MeshAxes(ep=2)
    mesh = topology.make_mesh(axes, jax.devices("cpu")[:2])
    eval_step, init_fn, tok_sh = make_sharded_eval_step(cfg, mesh)
    params = init_fn(jax.random.PRNGKey(0))
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64), tok_sh
    )
    got = float(eval_step(params, tokens))
    pure = float(loss_fn(params, tokens, cfg, mesh, include_aux=False))
    with_aux = float(loss_fn(params, tokens, cfg, mesh, include_aux=True))
    assert got == pytest.approx(pure, rel=1e-5)
    assert with_aux > pure  # the regularizers really were excluded
