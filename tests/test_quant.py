"""Weight-only int8 quantization (models/quant.py).

The contract: quantized serving is an approximation of the float model with
bounded per-matmul error (symmetric per-output-channel scales), the tree
mirrors the base tree, and the decode path consumes either transparently —
including tp-sharded serving with the quantized sharding specs."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import decode, quant, transformer as tm  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq_len=32, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


def setup(cfg, b=2, t=8, seed=0):
    params = tm.init_params(cfg, jax.random.PRNGKey(seed))
    prompt = jax.random.randint(
        jax.random.PRNGKey(seed + 1), (b, t), 0, cfg.vocab_size, jnp.int32
    )
    return params, prompt


class TestQuant:
    def test_roundtrip_error_is_bounded(self):
        """Per-output-channel symmetric int8: dequantized weights are within
        scale/2 of the originals elementwise (half a quantization step)."""
        cfg = cfg_of()
        params = tm.init_params(cfg, jax.random.PRNGKey(0))
        qp = quant.quantize_params(params, cfg)
        w = np.asarray(params["layers"]["wq"])
        deq = np.asarray(quant.load_weight(qp["layers"]["wq"], jnp.float32))
        step = np.asarray(qp["layers"]["wq"]["scale"])
        assert np.all(np.abs(w - deq) <= 0.5 * step + 1e-8)
        assert qp["layers"]["wq"]["qi8"].dtype == jnp.int8

    def test_norms_and_router_stay_float(self):
        cfg = cfg_of(n_experts=4)
        params = tm.init_params(cfg, jax.random.PRNGKey(0))
        qp = quant.quantize_params(params, cfg)
        assert not quant.is_quantized_leaf(qp["layers"]["attn_norm"])
        assert not quant.is_quantized_leaf(qp["layers"]["router"])
        assert not quant.is_quantized_leaf(qp["final_norm"])
        assert quant.is_quantized_leaf(qp["layers"]["w_gate"])

    def test_quantized_decode_tracks_float_decode(self):
        """int8 logits stay close to float logits, and wherever the float
        model is decisive (top-1 margin above the quantization noise) the
        quantized argmax agrees. Token-for-token equality is deliberately
        NOT asserted: a random-init model's near-uniform logits make greedy
        argmax unstable under any perturbation."""
        cfg = cfg_of()
        params, prompt = setup(cfg)
        qp = quant.quantize_params(params, cfg)
        cache_f = decode.init_kv_cache(cfg, 2, 8)
        cache_q = decode.init_kv_cache(cfg, 2, 8)
        lf, _ = decode.advance(params, cache_f, prompt, cfg)
        lq, _ = decode.advance(qp, cache_q, prompt, cfg)
        lf, lq = np.asarray(lf), np.asarray(lq)
        noise = np.abs(lf - lq).max()
        assert noise < 0.15
        top2 = np.sort(lf, axis=-1)
        margin = top2[..., -1] - top2[..., -2]
        decisive = margin > 2 * noise
        assert decisive.any()  # the check below must actually bite
        np.testing.assert_array_equal(
            lf.argmax(-1)[decisive], lq.argmax(-1)[decisive]
        )
        out_q = decode.generate(qp, prompt, cfg, 6)
        assert out_q.shape == (2, 6)

    def test_quantized_moe_decodes(self):
        cfg = cfg_of(n_experts=4, expert_capacity_factor=8.0)
        params, prompt = setup(cfg)
        qp = quant.quantize_params(params, cfg)
        out = decode.generate(qp, prompt, cfg, 4)
        assert out.shape == (2, 4)

    def test_rejects_unmerged_lora(self):
        cfg = cfg_of(lora_rank=2)
        params = tm.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="merge_lora"):
            quant.quantize_params(params, cfg)
        merged = tm.merge_lora(params, cfg)
        quant.quantize_params(merged, cfg_of())  # folded tree quantizes fine

    def test_tp_sharded_quantized_matches_single_device(self):
        from hivedscheduler_tpu.parallel import topology

        cfg = cfg_of(n_kv_heads=2)
        params, prompt = setup(cfg)
        qp = quant.quantize_params(params, cfg)
        want = decode.generate(qp, prompt, cfg, 6)
        mesh = topology.make_mesh(
            topology.MeshAxes(dp=2, tp=2), topology.get_devices(4)
        )
        run, param_sh, prompt_sh = decode.make_sharded_generate(
            cfg, mesh, 6, quantized=True
        )
        # the sharding tree must mirror the quantized tree exactly
        assert jax.tree.structure(param_sh) == jax.tree.structure(qp)
        got = run(jax.device_put(qp, param_sh), jax.device_put(prompt, prompt_sh))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("moe", [False, True])
    def test_tree_mirrors_init_params(self, moe):
        """CLAUDE.md guard rule for hand-rolled copies: the quantized tree
        (and quant.sharding_specs) must carry exactly init_params' keys, so
        a new param leaf cannot be silently dropped."""
        cfg = cfg_of(n_experts=4 if moe else 0)
        params = tm.init_params(cfg, jax.random.PRNGKey(0))
        qp = quant.quantize_params(params, cfg)
        assert set(qp) == set(params)
        assert set(qp["layers"]) == set(params["layers"])
        specs = quant.sharding_specs(cfg)
        assert set(specs) == set(params)
        assert set(specs["layers"]) == set(params["layers"])
        # quantized positions agree between the tree and the specs: a
        # {"qi8","scale"} leaf in one must be a {"qi8","scale"} dict in the
        # other, else device_put hits a tree-structure mismatch
        for k, v in qp["layers"].items():
            assert quant.is_quantized_leaf(v) == (
                isinstance(specs["layers"][k], dict)
                and set(specs["layers"][k]) == {"qi8", "scale"}
            ), k

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 7): quant x spec
    # composition; core quant exactness tests stay tier-1
    def test_quantized_target_speculation(self):
        """An int8 target verifies a float draft: greedy speculative output
        equals vanilla greedy decoding of the QUANTIZED target (exactness is
        w.r.t. the served model), locally and on a dp x tp mesh."""
        from hivedscheduler_tpu.models.speculative import (
            generate_speculative,
            make_sharded_speculative,
        )
        from hivedscheduler_tpu.parallel import topology

        tgt_cfg = cfg_of()
        dft_cfg = cfg_of(n_layers=1)
        params, prompt = setup(tgt_cfg)
        qp = quant.quantize_params(params, tgt_cfg)
        dft_params = tm.init_params(dft_cfg, jax.random.PRNGKey(9))
        want = decode.generate(qp, prompt, tgt_cfg, 7)
        got, _ = generate_speculative(
            qp, dft_params, prompt, tgt_cfg, dft_cfg, 7, gamma=2,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        mesh = topology.make_mesh(
            topology.MeshAxes(dp=2, tp=2), topology.get_devices(4)
        )
        run, tgt_sh, dft_sh, prompt_sh = make_sharded_speculative(
            tgt_cfg, dft_cfg, mesh, 7, gamma=2, quantized_target=True,
        )
        assert jax.tree.structure(tgt_sh) == jax.tree.structure(qp)
        got_sh, _ = run(
            jax.device_put(qp, tgt_sh),
            jax.device_put(dft_params, dft_sh),
            jax.device_put(prompt, prompt_sh),
        )
        np.testing.assert_array_equal(np.asarray(got_sh), np.asarray(want))
