"""Continuous-batching serving engine (models/serving.py).

The load-bearing property is exactness under interleaving: a request's
greedy output must be identical whether it runs alone through
``decode.generate`` or shares the engine with arbitrary other traffic
(admitted mid-flight into recycled slots, at a different row, at a
different time). Plus slot-recycling/occupancy accounting and the
validation surface."""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import decode, serving, transformer as tm  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=128, max_seq_len=128, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def vanilla(params, cfg, prompt, n):
    out = decode.generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, n,
        max_len=len(prompt) + n,
    )
    return [int(t) for t in np.asarray(out)[0]]


class TestServingEngine:
    def test_interleaved_requests_match_vanilla_generate(self, setup):
        cfg, params = setup
        prompts = [[5, 9, 2], [17, 3, 88, 41, 7], [1], [100, 22, 63, 4]]
        budgets = [6, 4, 8, 5]
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64)
        reqs = [eng.submit(p, n) for p, n in zip(prompts, budgets)]
        eng.run_until_drained()
        for req, p, n in zip(reqs, prompts, budgets):
            assert req.done
            assert req.tokens_out == vanilla(params, cfg, p, n), req.rid

    def test_mid_flight_submission_into_recycled_slot(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64)
        a = eng.submit([5, 9, 2], 3)
        b = eng.submit([17, 3], 9)
        for _ in range(4):  # a (3 tokens) finishes, its slot frees
            eng.step()
        assert a.done and not b.done
        c = eng.submit([100, 22, 63, 4], 5)  # lands in a's recycled slot
        eng.run_until_drained()
        assert b.tokens_out == vanilla(params, cfg, [17, 3], 9)
        assert c.tokens_out == vanilla(params, cfg, [100, 22, 63, 4], 5)

    def test_slot_recycling_occupancy(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=32)
        reqs = [eng.submit([i + 1, i + 2], 5) for i in range(6)]
        eng.run_until_drained()
        assert all(r.done and len(r.tokens_out) == 5 for r in reqs)
        # 6 requests through 2 slots: recycling keeps both slots busy nearly
        # the whole run
        assert eng.occupancy > 0.8, eng.occupancy

    def test_eos_retires_early_and_frees_slot(self, setup):
        cfg, params = setup
        # pick an eos whose FIRST occurrence in the reference stream is
        # strictly inside the budget: the tiny random model can emit
        # repeating tokens (observed: ref[0] == ref[2]), and a degenerate
        # choice would retire at the repeat instead of the tested position
        eos = None
        for prompt in ([5, 9, 2], [7, 11, 23], [3, 19, 42], [81, 2]):
            ref = vanilla(params, cfg, prompt, 6)
            for pos in range(1, 5):
                if ref[pos] not in ref[:pos]:
                    eos, eos_pos = ref[pos], pos
                    break
            if eos is not None:
                break
        assert eos is not None, "no non-degenerate eos position found"
        eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=32,
                                    eos_id=eos)
        r = eng.submit(prompt, 6)
        follower = eng.submit([17, 3], 2)  # only runs once r's slot frees
        eng.run_until_drained()
        # retired at the eos position, not the full budget of 6
        assert r.done and r.tokens_out == ref[:eos_pos + 1]
        assert r.finish_reason == "eos"
        # the follower drains too (and may itself hit eos early)
        assert follower.done and 1 <= len(follower.tokens_out) <= 2

    def test_sampling_smoke_and_validation(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=32,
                                    temperature=0.8, top_k=20, top_p=0.9)
        r = eng.submit([4, 8], 5)
        eng.run_until_drained()
        assert r.done and len(r.tokens_out) == 5
        assert all(0 <= t < cfg.vocab_size for t in r.tokens_out)
        with pytest.raises(ValueError, match="empty"):
            eng.submit([], 3)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit([1, 2], 0)
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit([1, 2], 64)

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 15): heavy
    # variant; tier-1 cousins: test_interleaved_requests_match_vanilla_
    # generate (greedy interleaving exactness) + test_sampling_smoke_and_
    # validation, and the sampled spec-serving determinism suite
    # (tests/test_serving_speculative_sampled.py)
    def test_sampled_streams_reproducible_under_interleaving(self, setup):
        """Counter-based sampling keys (fold_in(seed, rid, n_emitted)):
        a request's sampled stream is a function of (seed, rid, prompt)
        only — batch interleaving and arrival order must not change it."""
        cfg, params = setup
        kw = dict(max_batch=2, max_len=32, temperature=0.8, top_k=20,
                  top_p=0.9, seed=11)
        # engine A: both requests arrive together
        a = serving.ServingEngine(params, cfg, **kw)
        a0 = a.submit([4, 8], 5)
        a1 = a.submit([9, 1, 7], 6)
        a.run_until_drained()
        # engine B: same submission ORDER (same rids) but the second
        # request arrives mid-decode of the first — different interleaving
        b = serving.ServingEngine(params, cfg, **kw)
        b0 = b.submit([4, 8], 5)
        b.step()
        b.step()
        b1 = b.submit([9, 1, 7], 6)
        b.run_until_drained()
        assert a0.tokens_out == b0.tokens_out
        assert a1.tokens_out == b1.tokens_out

    def test_idle_row_lengths_clamp_at_arena(self, setup):
        """Retired/parked rows advance with every shared decode step; the
        clamp keeps their lengths (=> RoPE positions, scatter indices)
        inside the arena instead of drifting unboundedly (ADVICE r4)."""
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=32)
        short = eng.submit([5], 1)
        long_req = eng.submit([5, 9, 2], 25)
        eng.run_until_drained()
        assert short.done and long_req.done
        lengths = np.asarray(jax.device_get(eng.cache.lengths))
        assert (lengths <= 32).all()

    def test_quantized_params_serve_exactly(self, setup):
        """int8 weight-only trees (models/quant.py) flow through the engine
        unchanged — the shared quant-aware helpers (embed_tokens/load_weight)
        serve them — and match single-request quantized generate exactly."""
        from hivedscheduler_tpu.models import quant

        cfg, params = setup
        qparams = quant.quantize_params(params, cfg)
        eng = serving.ServingEngine(qparams, cfg, max_batch=2, max_len=64)
        a = eng.submit([5, 9, 2], 5)
        b = eng.submit([17, 3, 88], 4)
        eng.run_until_drained()
        out = decode.generate(
            qparams, jnp.asarray([[5, 9, 2]], jnp.int32), cfg, 5, max_len=8)
        assert a.tokens_out == [int(t) for t in np.asarray(out)[0]]
        assert b.done and len(b.tokens_out) == 4

    def test_sharded_engine_matches_unsharded(self, setup):
        """dp x tp engine layout: same greedy tokens as the single-device
        engine (GSPMD inserts the collectives; content is unchanged)."""
        from hivedscheduler_tpu.parallel import topology

        cfg, params = setup
        mesh = topology.make_mesh(
            topology.MeshAxes(dp=2, tp=2), topology.get_devices(4)
        )
        ref = vanilla(params, cfg, [5, 9, 2], 5)
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    mesh=mesh)
        a = eng.submit([5, 9, 2], 5)
        b = eng.submit([17, 3, 88, 41], 4)
        eng.run_until_drained()
        assert a.tokens_out == ref
        assert b.tokens_out == vanilla(params, cfg, [17, 3, 88, 41], 4)
        with pytest.raises(ValueError, match="max_batch"):
            serving.ServingEngine(params, cfg, max_batch=3, mesh=mesh)

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_fuzz_random_interleavings(self, setup):
        """Randomized schedule fuzz (same spirit as the scheduler's
        invariant harness): random prompts/budgets submitted at random step
        offsets against a small slot pool — every request's greedy output
        must still equal its solo run."""
        import random

        cfg, params = setup
        rng = random.Random(11)
        shared = [rng.randrange(1, cfg.vocab_size) for _ in range(12)]
        for trial in range(3):
            # trials cover the prefix cache off, tiny (evicting) and ample;
            # roughly half the prompts extend a shared prefix so hits occur
            eng = serving.ServingEngine(
                params, cfg, max_batch=2, max_len=64,
                prefix_cache_size=(0, 3, 16)[trial],
            )
            plan = []  # (submit_at_step, prompt, budget)
            for i in range(5):
                base = shared[:rng.randrange(4, 13)] if rng.random() < 0.5 else []
                plan.append((
                    rng.randrange(0, 12),
                    base + [rng.randrange(1, cfg.vocab_size) for _ in
                            range(rng.randrange(1, 9))],
                    rng.randrange(1, 7),
                ))
            plan.sort(key=lambda t: t[0])
            live = []
            step = 0
            while plan or eng.queue or any(eng.slots) or not live:
                while plan and plan[0][0] <= step:
                    _, p, n = plan.pop(0)
                    live.append((eng.submit(p, n), p, n))
                if not eng.step() and not plan:
                    break
                step += 1
            eng.run_until_drained()
            for req, p, n in live:
                assert req.done, (trial, req.rid)
                assert req.tokens_out == vanilla(params, cfg, p, n), (
                    trial, req.rid)
            if trial > 0:
                # the cached trials must actually exercise the prefix path
                # (seed-11 draws guarantee shared-base prompts), else a
                # silent matching regression degrades them to no-cache runs
                assert eng.prefix_hits > 0, trial

    def test_prefill_bucketing_bounds_compiles(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=64)
        assert eng._bucket(1) == 2
        assert eng._bucket(2) == 2
        assert eng._bucket(3) == 4
        assert eng._bucket(33) == 64
        assert eng._bucket(64) == 64


class TestPriorityAdmission:
    """submit(priority=...): higher priority jumps the queue when a slot
    frees; FIFO within a level; running rows are never preempted and no
    request's stream changes (scheduling-only, like chunked prefill)."""

    def test_high_priority_jumps_queue(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=32)
        first = eng.submit([5, 9, 2], 3)
        eng.step()                                  # first occupies the slot
        low_a = eng.submit([1, 2], 3)               # waits, prio 0
        low_b = eng.submit([3, 4], 3)               # waits, prio 0
        high = eng.submit([7, 8], 3, priority=5)    # arrives LAST
        assert [r.rid for r in eng.queue] == [high.rid, low_a.rid, low_b.rid]
        eng.run_until_drained()
        # the running row was never preempted; high got the slot next
        assert first.first_token_at < high.first_token_at
        assert high.first_token_at < low_a.first_token_at < low_b.first_token_at

    def test_fifo_within_priority_level(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=32)
        eng.submit([5], 2)
        eng.step()  # admit the slot-holder
        a = eng.submit([1, 2], 2, priority=3)
        b = eng.submit([3, 4], 2, priority=3)
        c = eng.submit([6, 7], 2, priority=9)
        assert [r.rid for r in eng.queue] == [c.rid, a.rid, b.rid]

    def test_priority_does_not_change_streams(self, setup):
        """Admission order is the ONLY effect: each request's tokens equal
        its run in a plain FIFO engine."""
        cfg, params = setup
        prompts = [[5, 9, 2], [17, 3, 88], [1, 4], [22, 60]]
        plain = serving.ServingEngine(params, cfg, max_batch=2, max_len=32)
        refs = [plain.submit(p, 4) for p in prompts]
        plain.run_until_drained()
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=32)
        reqs = [eng.submit(p, 4, priority=pr)
                for p, pr in zip(prompts, [0, 7, 0, 7])]
        eng.run_until_drained()
        for req, ref in zip(reqs, refs):
            assert req.tokens_out == ref.tokens_out, req.rid
