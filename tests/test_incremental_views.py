"""Differential guards for the incremental cluster views (perf PR).

The scheduler's persistent per-(chain, VC) cluster views defer work with
dirty tracking but must never change results:

- ``chaos.invariants.check_cluster_views`` pins every cached node counter,
  native score buffer and static enclosure structure bit-equal to a
  from-scratch rebuild — here driven over randomized allocate/release churn
  (the chaos soak harness runs the same check on its own seeds via
  ``check_all``);
- node SELECTION under the incremental path (cached order + static
  enclosures + native packing) is compared against the rebuild-per-call
  reference (:func:`_find_nodes_for_pods`) on identical live state. Equal
  sort keys make placements interchangeable (the pre-PR code's in-place
  ``cv.sort`` had history-dependent tie order too), so the comparison is on
  the picked nodes' score keys, with identity compared when keys are
  unambiguous.
"""

import random

import pytest

from hivedscheduler_tpu.api.config import Config, new_config
from hivedscheduler_tpu.api.types import (
    CellTypeSpec,
    MeshLevelSpec,
    MeshSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    VirtualCellSpec,
    VirtualClusterSpec,
)
from hivedscheduler_tpu.algorithm.constants import OPPORTUNISTIC_PRIORITY
from hivedscheduler_tpu.algorithm.hived import HivedAlgorithm
from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.chaos import invariants
from hivedscheduler_tpu.common.utils import to_json
from hivedscheduler_tpu.k8s.types import Container, Node, Pod
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod


def build_algo():
    mesh = MeshSpec(
        topology=(8, 8, 4),
        chip_type="chip",
        host_shape=(2, 2, 1),
        levels=[
            MeshLevelSpec(name="m8", shape=(2, 2, 2)),
            MeshLevelSpec(name="m16", shape=(4, 2, 2)),
            MeshLevelSpec(name="m32", shape=(4, 4, 2)),
            MeshLevelSpec(name="m64", shape=(4, 4, 4)),
            MeshLevelSpec(name="m128", shape=(8, 4, 4)),
        ],
    )
    cfg = new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={"pod256": CellTypeSpec(mesh=mesh)},
            physical_cells=[PhysicalCellSpec(cell_type="pod256",
                                             cell_address="p0")],
        ),
        virtual_clusters={
            "vc-a": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=1, cell_type="pod256.m128")]),
            "vc-b": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=2, cell_type="pod256.m64")]),
        },
    ))
    algo = HivedAlgorithm(cfg)
    nodes = sorted({
        n for ccl in algo.full_cell_list.values()
        for c in ccl[max(ccl)] for n in c.nodes
    })
    for n in nodes:
        algo.add_node(Node(name=n))
    return algo, nodes


def make_pod(name, vc, priority, group, pods, chips):
    spec = {
        "virtualCluster": vc,
        "priority": priority,
        "leafCellType": "chip",
        "leafCellNumber": chips,
        "affinityGroup": {
            "name": group,
            "members": [{"podNumber": pods, "leafCellNumber": chips}],
        },
    }
    return Pod(
        name=name, uid=name,
        annotations={C.ANNOTATION_POD_SCHEDULING_SPEC: to_json(spec)},
        containers=[Container(
            resource_limits={C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})],
    )


def schedule_gang(algo, nodes, vc, prio, group, pods, chips):
    bound = []
    for i in range(pods):
        pod = make_pod(f"{group}-{i}", vc, prio, group, pods, chips)
        r = algo.schedule(pod, nodes, FILTERING_PHASE)
        if r.pod_bind_info is None:
            for bp in bound:
                algo.delete_allocated_pod(bp)
            return None
        bp = new_binding_pod(pod, r.pod_bind_info)
        algo.add_allocated_pod(bp)
        bound.append(bp)
    return bound


def _node_key(s, n):
    sign = -1 if s.pack else 1
    return (not n.healthy, not n.suggested,
            sign * n.used_leaf_cell_num_same_priority,
            n.used_leaf_cell_num_higher_priority,
            n.free_leaf_cell_num_at_priority)


def _all_schedulers(algo):
    yield from (s for _, s in invariants._all_topology_schedulers(algo))


@pytest.mark.parametrize("seed", range(4))
def test_views_bit_equal_to_rebuild_under_churn(seed):
    """Random allocate/release churn; after every step the cached views must
    compare equal to a from-scratch rebuild (check_cluster_views recomputes
    every 'current' node counter and the static structures)."""
    rng = random.Random(seed)
    algo, nodes = build_algo()
    live = {}
    gid = 0
    for step in range(30):
        if live and rng.random() < 0.4:
            name = rng.choice(sorted(live))
            for bp in live.pop(name):
                algo.delete_allocated_pod(bp)
        else:
            vc = rng.choice(["vc-a", "vc-b"])
            prio = rng.choice([-1, 0, 5, 10])
            pods, chips = rng.choice([(1, 4), (2, 4), (4, 4), (8, 4), (1, 8)])
            name = f"g{gid}"
            gid += 1
            bound = schedule_gang(algo, nodes, vc, prio, name, pods, chips)
            if bound:
                live[name] = bound
        # occasional health churn so bad/healthy transitions are covered
        if rng.random() < 0.15:
            node = rng.choice(nodes)
            algo.update_node(
                Node(name=node),
                Node(name=node, unschedulable=True),
            )
            algo.update_node(
                Node(name=node, unschedulable=True),
                Node(name=node),
            )
        invariants.check_cluster_views(algo, ctx=f"seed {seed} step {step}")
        invariants.check_all(algo, ctx=f"seed {seed} step {step}")


@pytest.mark.parametrize("seed", range(4))
def test_incremental_node_selection_matches_rebuild(seed):
    """On identical live state, the incremental path (cached order + static
    enclosures + native packing when available) must pick nodes with exactly
    the same score keys as the rebuild-per-call reference — both searches
    are read-only, so they are compared directly on the live schedulers
    after every churn step."""
    rng = random.Random(100 + seed)
    algo, nodes = build_algo()
    live = {}
    gid = 0
    for step in range(20):
        if live and rng.random() < 0.4:
            name = rng.choice(sorted(live))
            for bp in live.pop(name):
                algo.delete_allocated_pod(bp)
        else:
            vc = rng.choice(["vc-a", "vc-b"])
            pods, chips = rng.choice([(1, 4), (2, 4), (4, 4), (8, 4)])
            name = f"g{gid}"
            gid += 1
            bound = schedule_gang(algo, nodes, vc,
                                  rng.choice([-1, 0, 5]), name, pods, chips)
            if bound:
                live[name] = bound
        for s in _all_schedulers(algo):
            for nums in ([4], [4, 4], [4, 4, 4, 4], [8, 8]):
                s._update_cluster_view(
                    OPPORTUNISTIC_PRIORITY, set(), True
                )
                picked_inc, reason_inc = s._find_nodes(list(nums), True)
                picked_ref, reason_ref = s._find_nodes(list(nums), False)
                if picked_inc is None or picked_ref is None:
                    assert picked_inc is None and picked_ref is None, (
                        step, nums, picked_inc, picked_ref)
                    assert reason_inc == reason_ref, (reason_inc, reason_ref)
                else:
                    keys_inc = [_node_key(s, s.cv[i]) for i in picked_inc]
                    keys_ref = [_node_key(s, s.cv[i]) for i in picked_ref]
                    assert keys_inc == keys_ref, (step, nums)


def test_ancestor_matrix_static_and_cached():
    """The per-node ancestor matrices feeding the C++ in-node search are
    built once and must stay valid across health churn (they encode pure
    topology): same object, same contents."""
    from hivedscheduler_tpu.algorithm import topology_aware as ta

    algo, nodes = build_algo()
    chain = next(iter(algo.full_cell_list))
    node_cell = algo.full_cell_list[chain][3][0]  # some mid-level cell
    m1 = ta._node_ancestor_matrix(node_cell)
    # health churn must not invalidate topology
    algo.update_node(Node(name=nodes[0]),
                     Node(name=nodes[0], unschedulable=True))
    m2 = ta._node_ancestor_matrix(node_cell)
    assert m1 is m2  # cached, not rebuilt per pod
    row_of, flat, n_levels = m2
    assert n_levels == node_cell.level
    assert len(row_of) == node_cell.total_leaf_cell_num
