"""int8 KV cache in the continuous-batching engine (kv_dtype="int8").

Exactness contract: quantization happens ONCE at scatter time, and every
engine composition re-reads the same quantized entries — so int8 engines
are BIT-EXACT among themselves (chunked == monolithic, prefix-cache ==
plain, greedy speculation == plain int8 decode). Only int8-vs-float is
approximate, bounded by the symmetric absmax step (absmax/127 per
element) — asserted on logits, not streams (random tiny models argmax
near ties)."""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import serving, transformer as tm  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=96, d_model=48, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=96, max_seq_len=128, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


LONG = list(range(20, 52))


def run_all(cfg, params, prompts, budget=5, **kw):
    eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=96,
                                kv_dtype="int8", **kw)
    reqs = [eng.submit(p, budget) for p in prompts]
    eng.run_until_drained()
    return eng, [r.tokens_out for r in reqs]


def test_quantization_error_bound():
    """dequant(quant(x)) is within one quantization step (absmax/127)."""
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 3, 2, 16)) * 5.0
    q, scale = serving._quant_kv(x)
    deq = q.astype(jnp.float32) * scale[..., None]
    step = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
    assert bool(jnp.all(jnp.abs(deq - x) <= step + 1e-6))
    assert q.dtype == jnp.int8 and scale.dtype == jnp.float32


def test_invalid_kv_dtype_rejected(setup):
    cfg, params = setup
    with pytest.raises(ValueError, match="kv_dtype"):
        serving.ServingEngine(params, cfg, kv_dtype="fp8")


def test_int8_vs_float_logits_bounded(setup):
    """One prefill + one decode step: quantized-cache logits stay close
    to the float-cache logits (the only approximate comparison)."""
    cfg, params = setup
    toks = jnp.asarray([[5, 9, 2, 44, 17, 8, 30, 2]], jnp.int32)
    c8 = serving.init_ragged_cache(cfg, 1, 32, kv_dtype="int8")
    cf = serving.init_ragged_cache(cfg, 1, 32)
    l8, c8 = serving.advance_ragged(params, c8, toks, cfg, row=jnp.int32(0),
                                    start=jnp.int32(0))
    lf, cf = serving.advance_ragged(params, cf, toks, cfg, row=jnp.int32(0),
                                    start=jnp.int32(0))
    c8 = c8._replace(lengths=c8.lengths.at[0].set(8))
    cf = cf._replace(lengths=cf.lengths.at[0].set(8))
    np.testing.assert_allclose(np.asarray(l8), np.asarray(lf), atol=0.25)
    nxt = jnp.asarray([int(jnp.argmax(lf[0, -1]))], jnp.int32)
    d8, _ = serving.advance_ragged(params, c8, nxt[:, None], cfg)
    df, _ = serving.advance_ragged(params, cf, nxt[:, None], cfg)
    np.testing.assert_allclose(np.asarray(d8), np.asarray(df), atol=0.25)


@pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): int8 x chunked
# composition variant; tier-1 cousins: the float chunked parity
# (test_serving_chunked.py::test_chunked_matches_monolithic[4]) + the
# int8-vs-float base guards above
def test_int8_chunked_matches_int8_monolithic(setup):
    """Chunking is still a pure scheduling change inside the int8 world:
    the chunks quantize the same values in the same positions."""
    cfg, params = setup
    prompts = [LONG, [7, 8, 9], LONG + [5]]
    _, plain = run_all(cfg, params, prompts)
    eng, chunked = run_all(cfg, params, prompts, prefill_chunk=8)
    assert chunked == plain
    assert eng.prefill_chunks_done > 0


@pytest.mark.slow  # tier-1 wall-time budget (ISSUE 15): composition
# variant; tier-1 cousins: test_int8_vs_float_logits_bounded (int8 core)
# and the dense prefix exactness suite (tests/test_serving_prefix.py)
def test_int8_prefix_cache_matches_int8_plain(setup):
    """A restored quantized prefix (values + scales travel together) is
    bit-identical to the stored row."""
    cfg, params = setup
    prompts = [LONG + [1], LONG + [2, 3], LONG + [1, 4]]
    _, plain = run_all(cfg, params, prompts)
    eng, cached = run_all(cfg, params, prompts, prefix_cache_size=16)
    assert cached == plain
    assert eng.prefix_hits >= 1


@pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
def test_int8_speculation_matches_int8_plain_greedy(setup):
    """Greedy speculation inside the int8 world equals plain int8 decode:
    the verify window quantizes and attends the same entries step-by-step
    decode would."""
    cfg, params = setup
    dcfg = cfg_of(n_layers=1, d_model=24, n_heads=2, n_kv_heads=1, d_ff=48)
    dparams = tm.init_params(dcfg, jax.random.PRNGKey(5))
    prompts = [[5, 9, 2], [17, 3, 88, 41], [1, 2]]
    _, plain = run_all(cfg, params, prompts)
    eng = serving.SpeculativeServingEngine(
        params, cfg, dparams, dcfg, gamma=2, max_batch=2, max_len=96,
        kv_dtype="int8",
    )
    reqs = [eng.submit(p, 5) for p in prompts]
    eng.run_until_drained()
    assert [r.tokens_out for r in reqs] == plain
    assert eng.drafted > 0


def test_int8_mesh_sharded_matches_unsharded(setup):
    """dp x tp layout with quantized cache (scales shard alongside the
    kv-head axis): same int8 streams as the single-device int8 engine."""
    from hivedscheduler_tpu.parallel import topology

    cfg, params = setup
    prompts = [[5, 9, 2], [17, 3, 88, 41]]
    _, plain = run_all(cfg, params, prompts)
    mesh = topology.make_mesh(topology.MeshAxes(dp=2, tp=2),
                              topology.get_devices(4))
    eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=96,
                                kv_dtype="int8", mesh=mesh)
    reqs = [eng.submit(p, 5) for p in prompts]
    eng.run_until_drained()
    assert [r.tokens_out for r in reqs] == plain


@pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
def test_kitchen_sink_composition(setup):
    """Every serving feature at once — MoE target, int8 KV, chunked
    prefill, prefix cache, greedy speculation with a dense draft — must
    equal the plain int8-KV MoE engine bit for bit (each feature is a
    scheduling/representation change below the routing/attention math)."""
    moe_cfg = cfg_of(n_experts=4, moe_top_k=2)
    params = tm.init_params(moe_cfg, jax.random.PRNGKey(11))
    dcfg = cfg_of(n_layers=1, d_model=24, n_heads=2, n_kv_heads=1, d_ff=48)
    dparams = tm.init_params(dcfg, jax.random.PRNGKey(12))
    prompts = [LONG + [1], [7, 8], LONG + [1, 9], LONG + [2]]

    _, refs = run_all(moe_cfg, params, prompts)

    eng = serving.SpeculativeServingEngine(
        params, moe_cfg, dparams, dcfg, gamma=2, max_batch=2, max_len=96,
        kv_dtype="int8", prefill_chunk=8, prefix_cache_size=16,
    )
    reqs = [eng.submit(p, 5) for p in prompts]
    eng.run_until_drained()
    assert [r.tokens_out for r in reqs] == refs
    assert eng.prefill_chunks_done > 0 and eng.drafted > 0
    assert eng.prefix_hits >= 1
