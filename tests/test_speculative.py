"""Speculative decoding (models/speculative.py).

The load-bearing property is exactness: speculation is an acceleration, not
an approximation. Greedy speculative output must be bit-identical to vanilla
greedy decoding regardless of draft quality; sampled speculation with
draft == target must accept every proposal (the rejection test u < p_t/p_d
degenerates to u < 1).
"""

import numpy as np
import pytest

pytest.importorskip("jax")  # jax-less image builds run the scheduler suite

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import decode, transformer as tm  # noqa: E402
from hivedscheduler_tpu.models.speculative import generate_speculative  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=97, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq_len=128, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


def setup(cfg, b=2, t=7, seed=0):
    params = tm.init_params(cfg, jax.random.PRNGKey(seed))
    prompt = jax.random.randint(
        jax.random.PRNGKey(seed + 1), (b, t), 0, cfg.vocab_size, jnp.int32
    )
    return params, prompt


class TestSpeculative:
    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 15): heavy
    # variant; tier-1 cousins: test_self_draft_accepts_everything +
    # test_jits_whole_loop here, and the serving-level greedy spec-decode
    # parity suite (tests/test_serving_speculative.py)
    def test_greedy_matches_vanilla(self):
        """Greedy speculative == target-only greedy, even with an unrelated
        random draft model (rejections just fall back to the target argmax)."""
        tgt_cfg = cfg_of()
        dft_cfg = cfg_of(d_model=16, n_layers=1, n_heads=2, d_ff=32)
        tgt_params, prompt = setup(tgt_cfg)
        dft_params = tm.init_params(dft_cfg, jax.random.PRNGKey(42))
        want = decode.generate(tgt_params, prompt, tgt_cfg, 14)
        for gamma in (1, 3, 5):
            got, stats = generate_speculative(
                tgt_params, dft_params, prompt, tgt_cfg, dft_cfg, 14,
                gamma=gamma,
            )
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
            assert int(stats.rounds) >= 1
            assert 0 <= int(stats.accepted) <= int(stats.drafted)

    def test_self_draft_accepts_everything(self):
        """draft == target => acceptance probability 1 at every position, so
        each round accepts all gamma proposals."""
        cfg = cfg_of()
        params, prompt = setup(cfg)
        got, stats = generate_speculative(
            params, params, prompt, cfg, cfg, 12, gamma=4,
            temperature=0.8, key=jax.random.PRNGKey(3),
        )
        assert got.shape == (2, 12)
        assert int(stats.accepted) == int(stats.drafted)

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_sampled_output_is_valid_and_deterministic(self):
        tgt_cfg = cfg_of()
        dft_cfg = cfg_of(d_model=16, n_layers=1, n_heads=2, d_ff=32)
        tgt_params, prompt = setup(tgt_cfg)
        dft_params = tm.init_params(dft_cfg, jax.random.PRNGKey(7))
        kw = dict(gamma=3, temperature=1.0, top_k=20, top_p=0.9,
                  key=jax.random.PRNGKey(11))
        a, stats = generate_speculative(
            tgt_params, dft_params, prompt, tgt_cfg, dft_cfg, 10, **kw)
        b, _ = generate_speculative(
            tgt_params, dft_params, prompt, tgt_cfg, dft_cfg, 10, **kw)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.asarray(a).min() >= 0 and np.asarray(a).max() < tgt_cfg.vocab_size
        assert int(stats.drafted) == 3 * int(stats.rounds)

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_greedy_exact_with_gqa_target(self):
        """Compact-GQA target + dense draft still greedy-exact."""
        tgt_cfg = cfg_of(n_heads=4, n_kv_heads=2)
        dft_cfg = cfg_of(d_model=16, n_layers=1, n_heads=2, d_ff=32)
        tgt_params, prompt = setup(tgt_cfg, b=1)
        dft_params = tm.init_params(dft_cfg, jax.random.PRNGKey(5))
        want = decode.generate(tgt_params, prompt, tgt_cfg, 9)
        got, _ = generate_speculative(
            tgt_params, dft_params, prompt, tgt_cfg, dft_cfg, 9, gamma=2,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_jits_whole_loop(self):
        tgt_cfg = cfg_of()
        dft_cfg = cfg_of(n_layers=1)
        tgt_params, prompt = setup(tgt_cfg)
        dft_params = tm.init_params(dft_cfg, jax.random.PRNGKey(1))
        jitted = jax.jit(
            lambda tp, dp, pr: generate_speculative(
                tp, dp, pr, tgt_cfg, dft_cfg, 8, gamma=3
            )
        )
        got, _ = jitted(tgt_params, dft_params, prompt)
        want = decode.generate(tgt_params, prompt, tgt_cfg, 8)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestShardedSpeculative:
    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_tp_sharded_speculation_matches_single_device(self):
        """dp=2 x tp=2 speculative greedy == single-device speculative ==
        vanilla greedy (the draft here shards over tp too)."""
        from hivedscheduler_tpu.models.speculative import make_sharded_speculative
        from hivedscheduler_tpu.parallel import topology

        # vocab/ff/width all divide tp=2 (the sharded-serving contract)
        tgt_cfg = cfg_of(n_kv_heads=2, vocab_size=96)
        dft_cfg = cfg_of(n_layers=1, vocab_size=96)
        tgt_params, prompt = setup(tgt_cfg)
        dft_params = tm.init_params(dft_cfg, jax.random.PRNGKey(8))
        want = decode.generate(tgt_params, prompt, tgt_cfg, 9)
        mesh = topology.make_mesh(
            topology.MeshAxes(dp=2, tp=2), topology.get_devices(4)
        )
        run, tgt_sh, dft_sh, prompt_sh = make_sharded_speculative(
            tgt_cfg, dft_cfg, mesh, 9, gamma=3,
        )
        got, stats = run(
            jax.device_put(tgt_params, tgt_sh),
            jax.device_put(dft_params, dft_sh),
            jax.device_put(prompt, prompt_sh),
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert int(stats.rounds) >= 1

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_indivisible_draft_heads_replicate(self):
        """A draft whose heads don't divide tp is replicated, not rejected."""
        from hivedscheduler_tpu.models.speculative import make_sharded_speculative
        from hivedscheduler_tpu.parallel import topology

        tgt_cfg = cfg_of(vocab_size=96)
        dft_cfg = cfg_of(n_heads=1, d_model=16, n_layers=1, d_ff=32,
                         vocab_size=96)
        tgt_params, prompt = setup(tgt_cfg, b=2)
        dft_params = tm.init_params(dft_cfg, jax.random.PRNGKey(8))
        want = decode.generate(tgt_params, prompt, tgt_cfg, 6)
        mesh = topology.make_mesh(
            topology.MeshAxes(dp=2, tp=2), topology.get_devices(4)
        )
        run, tgt_sh, dft_sh, prompt_sh = make_sharded_speculative(
            tgt_cfg, dft_cfg, mesh, 6, gamma=2,
        )
        from jax.sharding import PartitionSpec as P
        flat = jax.tree.leaves(dft_sh)
        assert all(s.spec == P() for s in flat)
        got, _ = run(
            jax.device_put(tgt_params, tgt_sh),
            jax.device_put(dft_params, dft_sh),
            jax.device_put(prompt, prompt_sh),
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_sharded_rejects_indivisible_target_heads(self):
        from hivedscheduler_tpu.models.speculative import make_sharded_speculative
        from hivedscheduler_tpu.parallel import topology

        mesh = topology.make_mesh(topology.MeshAxes(tp=4), topology.get_devices(4))
        with pytest.raises(ValueError, match="divide the tp axis"):
            make_sharded_speculative(cfg_of(n_heads=2), cfg_of(), mesh, 4)
