"""Differential guard for the fused bookkeeping walks.

``allocate_cell_walk`` / ``release_cell_walk`` fuse the reference's
``setCellPriority`` (cell_allocation.go:425-441) and
``updateUsedLeafCellNumAtPriority`` (cell_allocation.go:445-454) into one
leaf->root walk on the allocation hot path.  This test drives randomized
allocate/release sequences over a real physical cell tree twice — once with
the fused walks, once with the exact two-step composition — and asserts the
entire tree state (priority, api mirrors, used-count dicts) is identical
after every step.
"""

import os
import random

import pytest

from hivedscheduler_tpu.algorithm.cell_allocation import (
    allocate_cell_walk,
    release_cell_walk,
    set_cell_priority,
    update_used_leaf_cell_num_at_priority,
)
from hivedscheduler_tpu.algorithm.config_parser import parse_config
from hivedscheduler_tpu.algorithm.constants import FREE_PRIORITY
from hivedscheduler_tpu.api.config import load_config

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)


def _fresh_tree():
    parsed = parse_config(load_config(FIXTURE))
    return parsed.physical_full_list["v5p-64"]


def _leaves(ccl):
    return list(ccl[min(ccl)])


def _snapshot(ccl):
    out = []
    for level in sorted(ccl):
        for c in ccl[level]:
            out.append(
                (
                    c.address,
                    c.priority,
                    c.api_status.cell_priority,
                    dict(c.used_leaf_cell_num_at_priorities),
                )
            )
    return out


def _composed_alloc(c, p):
    set_cell_priority(c, p)
    update_used_leaf_cell_num_at_priority(c, p, True)


def _composed_release(c, old_p):
    update_used_leaf_cell_num_at_priority(c, old_p, False)
    set_cell_priority(c, FREE_PRIORITY)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fused_walks_match_composition(seed):
    fused_ccl, comp_ccl = _fresh_tree(), _fresh_tree()
    fused_leaves, comp_leaves = _leaves(fused_ccl), _leaves(comp_ccl)
    assert [c.address for c in fused_leaves] == [c.address for c in comp_leaves]

    rng = random.Random(seed)
    allocated = {}  # index -> priority
    for step in range(400):
        if allocated and (rng.random() < 0.45 or len(allocated) == len(fused_leaves)):
            i = rng.choice(list(allocated))
            p = allocated.pop(i)
            release_cell_walk(fused_leaves[i], fused_leaves[i].priority)
            _composed_release(comp_leaves[i], comp_leaves[i].priority)
        else:
            free = [i for i in range(len(fused_leaves)) if i not in allocated]
            i = rng.choice(free)
            p = rng.choice([-1, 0, 1, 5, 10, 1000])
            allocated[i] = p
            allocate_cell_walk(fused_leaves[i], p)
            _composed_alloc(comp_leaves[i], p)
        assert _snapshot(fused_ccl) == _snapshot(comp_ccl), f"diverged at step {step}"


def test_fused_alloc_falls_back_on_priority_drop():
    ccl, ccl2 = _fresh_tree(), _fresh_tree()
    leaf, leaf2 = _leaves(ccl)[0], _leaves(ccl2)[0]
    allocate_cell_walk(leaf, 10)
    _composed_alloc(leaf2, 10)
    # re-allocating the same leaf at a lower priority is a priority *drop*:
    # the fused walk must take the exact composition fallback
    allocate_cell_walk(leaf, 1)
    _composed_alloc(leaf2, 1)
    assert _snapshot(ccl) == _snapshot(ccl2)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_batched_walks_match_composition(seed):
    """Group-lifecycle loops defer the count half of the walks to a
    ``UsedCountBatch`` flushed once per gang; after each flush the whole tree
    state must equal the exact per-leaf two-step composition."""
    from hivedscheduler_tpu.algorithm.cell_allocation import UsedCountBatch

    batch_ccl, comp_ccl = _fresh_tree(), _fresh_tree()
    batch_leaves, comp_leaves = _leaves(batch_ccl), _leaves(comp_ccl)

    rng = random.Random(seed)
    allocated = {}  # index -> priority
    for gang in range(60):
        batch = UsedCountBatch()
        # a "gang": several leaf ops deferred into one flush, like
        # _create/_delete_allocated_affinity_group do
        n_ops = rng.randint(1, 6)
        for _ in range(n_ops):
            if allocated and (rng.random() < 0.45 or len(allocated) == len(batch_leaves)):
                i = rng.choice(list(allocated))
                allocated.pop(i)
                release_cell_walk(batch_leaves[i], batch_leaves[i].priority, batch)
                _composed_release(comp_leaves[i], comp_leaves[i].priority)
            else:
                free = [i for i in range(len(batch_leaves)) if i not in allocated]
                i = rng.choice(free)
                p = rng.choice([-1, 0, 1, 5, 10, 1000])
                allocated[i] = p
                allocate_cell_walk(batch_leaves[i], p, batch)
                _composed_alloc(comp_leaves[i], p)
            # priorities (and their api mirrors) must be exact mid-batch:
            # the group loops read them between leaves
            prio = [(c.address, c.priority, c.api_status.cell_priority)
                    for lv in sorted(batch_ccl) for c in batch_ccl[lv]]
            prio2 = [(c.address, c.priority, c.api_status.cell_priority)
                     for lv in sorted(comp_ccl) for c in comp_ccl[lv]]
            assert prio == prio2, f"priorities diverged mid-batch at gang {gang}"
        batch.flush()
        assert _snapshot(batch_ccl) == _snapshot(comp_ccl), f"diverged after gang {gang}"
