"""obs subsystem tests: the span tracer (ring bound, no-op fast path,
Chrome-trace schema), scheduler decision traces through the real
HivedAlgorithm ladder, and the webserver's /v1/inspect/traces endpoints.

No jax needed — the algorithm layer is pure Python; the serving/train
emitters are covered in tests/test_obs_workloads.py.
"""

import json
import logging
import os
import threading

import pytest

from helpers import make_pod, set_healthy_nodes, validate_chrome_trace

from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.obs import decisions as obs_decisions
from hivedscheduler_tpu.obs import trace as obs_trace
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

logging.getLogger().setLevel(logging.ERROR)

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)


@pytest.fixture(autouse=True)
def _obs_isolation():
    """Every test starts with observability off and empty rings; global
    state never leaks into other tests."""
    obs_trace.disable()
    obs_trace.TRACER.clear()
    obs_decisions.RECORDER.disable()
    obs_decisions.RECORDER.clear()
    obs_decisions.RECORDER.on_commit = None
    yield
    obs_trace.disable()
    obs_trace.TRACER.clear()
    obs_decisions.RECORDER.disable()
    obs_decisions.RECORDER.clear()
    obs_decisions.RECORDER.on_commit = None


# ---------------------------------------------------------------- tracer


class TestTracer:
    def test_disabled_is_noop_and_allocation_free(self):
        assert not obs_trace.enabled()
        sp = obs_trace.span("x", cat="t", a=1)
        sp2 = obs_trace.span("y")
        assert sp is sp2  # the shared no-op object: no allocation per call
        with sp:
            sp.add(outcome="whatever")
        obs_trace.instant("z", b=2)
        obs_trace.complete("w", 0.0, 1.0)
        assert len(obs_trace.TRACER) == 0

    def test_span_records_complete_event(self):
        obs_trace.enable()
        with obs_trace.span("work", cat="unit", k="v") as sp:
            sp.add(outcome="done")
        events = [e for e in obs_trace.TRACER.snapshot() if e["ph"] == "X"]
        assert len(events) == 1
        ev = events[0]
        assert ev["name"] == "work" and ev["cat"] == "unit"
        assert ev["args"] == {"k": "v", "outcome": "done"}
        assert ev["dur"] >= 0

    def test_span_tags_exceptions(self):
        obs_trace.enable()
        with pytest.raises(ValueError):
            with obs_trace.span("boom"):
                raise ValueError("nope")
        ev = [e for e in obs_trace.TRACER.snapshot() if e["ph"] == "X"][0]
        assert ev["args"]["error"] == "ValueError"

    def test_ring_is_bounded(self):
        t = obs_trace.Tracer(capacity=8)
        for i in range(20):
            t.instant(f"e{i}")
        assert len(t) == 8
        assert t.dropped == 12
        names = [e["name"] for e in t.snapshot()]
        assert names == [f"e{i}" for i in range(12, 20)]  # newest kept

    def test_chrome_export_schema_and_json_round_trip(self, tmp_path):
        obs_trace.enable()
        with obs_trace.span("a", cat="c1", x=1):
            pass
        obs_trace.instant("marker", cat="c2")
        obs_trace.TRACER.complete("explicit", 1.0, 2.5, cat="c3",
                                  tid=7, args={"rid": 7})
        path = tmp_path / "trace.json"
        obs_trace.write_chrome_trace(str(path))
        obj = json.loads(path.read_text())
        events = validate_chrome_trace(obj)
        assert {e["name"] for e in events} >= {"a", "marker", "explicit"}
        explicit = next(e for e in events if e["name"] == "explicit")
        assert explicit["tid"] == 7
        assert explicit["dur"] == pytest.approx(1.5e6)  # 1.5 s in us

    def test_concurrent_emit_is_safe(self):
        obs_trace.enable(capacity=100_000)

        def emit(tid):
            for i in range(500):
                obs_trace.instant(f"t{tid}-{i}")

        threads = [threading.Thread(target=emit, args=(t,)) for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # metadata event from enable() + all 2000 instants
        assert len(obs_trace.TRACER) == 2001


# ------------------------------------------------------- decision traces


def fresh_algo():
    algo = HivedAlgorithm(load_config(FIXTURE))
    nodes = set_healthy_nodes(algo)
    return algo, nodes


class TestDecisionTraces:
    def test_disabled_records_nothing(self):
        algo, nodes = fresh_algo()
        pod = make_pod("p", {"virtualCluster": "vc2", "priority": 0,
                             "chipType": "v5e-chip", "chipNumber": 8})
        algo.schedule(pod, nodes, FILTERING_PHASE)
        assert obs_decisions.RECORDER.last() == []

    def test_bind_decision_explains_attempts(self):
        obs_decisions.RECORDER.enable()
        algo, nodes = fresh_algo()
        pod = make_pod("p", {"virtualCluster": "vc2", "priority": 0,
                             "chipType": "v5e-chip", "chipNumber": 8})
        r = algo.schedule(pod, nodes, FILTERING_PHASE)
        assert r.pod_bind_info is not None
        items = obs_decisions.RECORDER.last()
        assert len(items) == 1
        d = items[0]
        assert d["pod"] == "p(default/p)"
        assert d["vc"] == "vc2" and d["priority"] == 0
        assert d["phase"] == FILTERING_PHASE
        assert d["outcome"] == "bind" and d["node"]
        assert d["elapsedMs"] > 0
        # the ladder probed at least one chain on the guaranteed path and
        # the winning attempt is marked placed
        assert d["attempts"], "no placement attempts recorded"
        placed = [a for a in d["attempts"] if a["outcome"] == "placed"]
        assert placed and placed[-1]["path"] in ("guaranteed", "opportunistic")
        assert any(a["where"].startswith(("chain ", "pinned cell "))
                   for a in d["attempts"])

    def test_wait_decision_carries_reason(self):
        obs_decisions.RECORDER.enable()
        algo, nodes = fresh_algo()
        # vc2 guarantees a single v5e-8: a 16-chip guaranteed gang can't fit
        pod = make_pod("big", {"virtualCluster": "vc2", "priority": 0,
                               "chipType": "v5e-chip", "chipNumber": 16})
        r = algo.schedule(pod, nodes, FILTERING_PHASE)
        assert r.pod_bind_info is None
        d = obs_decisions.RECORDER.last()[0]
        assert d["outcome"] == "wait"
        failed = [a for a in d["attempts"] if a["outcome"] == "failed"]
        assert failed and all(a["reason"] for a in failed)

    def test_existing_group_attempt_recorded(self):
        obs_decisions.RECORDER.enable()
        algo, nodes = fresh_algo()
        spec = {"virtualCluster": "vc2", "priority": 1, "chipType": "v5p-chip",
                "chipNumber": 4,
                "affinityGroup": {"name": "g",
                                  "members": [{"podNumber": 2,
                                               "chipNumber": 4}]}}
        p0 = make_pod("g-0", spec)
        r0 = algo.schedule(p0, nodes, FILTERING_PHASE)
        algo.add_allocated_pod(new_binding_pod(p0, r0.pod_bind_info))
        p1 = make_pod("g-1", spec)
        algo.schedule(p1, nodes, FILTERING_PHASE)
        d = obs_decisions.RECORDER.last()[0]
        assert d["pod"] == "g-1(default/g-1)"
        assert any(a["path"] == "existing-allocated" and a["outcome"] == "placed"
                   for a in d["attempts"])

    def test_error_decision_committed(self):
        obs_decisions.RECORDER.enable()
        algo, nodes = fresh_algo()
        pod = make_pod("bad", {"virtualCluster": "no-such-vc", "priority": 0,
                               "chipType": "v5e-chip", "chipNumber": 8})
        with pytest.raises(Exception):
            algo.schedule(pod, nodes, FILTERING_PHASE)
        d = obs_decisions.RECORDER.last()[0]
        assert d["outcome"] == "error" and "no-such-vc" in d["reason"]

    def test_ring_bound_and_most_recent_first(self):
        rec = obs_decisions.DecisionRecorder(capacity=3)
        rec.enable()
        for i in range(5):
            d = rec.begin(f"default/p{i}", FILTERING_PHASE)
            d.finish("wait", reason="r")
            rec.commit(d)
        items = rec.last()
        assert [i["pod"] for i in items] == ["default/p4", "default/p3",
                                             "default/p2"]
        assert [i["pod"] for i in rec.last(1)] == ["default/p4"]

    def test_explain_line_and_commit_callback(self):
        obs_decisions.RECORDER.enable()
        seen = []
        obs_decisions.RECORDER.on_commit = lambda d: seen.append(d.explain())
        algo, nodes = fresh_algo()
        pod = make_pod("p", {"virtualCluster": "vc2", "priority": 0,
                             "chipType": "v5e-chip", "chipNumber": 8})
        algo.schedule(pod, nodes, FILTERING_PHASE)
        assert len(seen) == 1
        line = seen[0]
        assert "default/p" in line and "-> bind" in line and "vc=vc2" in line

    def test_decisions_mirror_into_trace_timeline(self):
        obs_trace.enable()
        obs_decisions.RECORDER.enable()
        algo, nodes = fresh_algo()
        pod = make_pod("p", {"virtualCluster": "vc2", "priority": 0,
                             "chipType": "v5e-chip", "chipNumber": 8})
        algo.schedule(pod, nodes, FILTERING_PHASE)
        names = [e["name"] for e in obs_trace.TRACER.snapshot()]
        assert "schedule p(default/p)" in names


# ------------------------------------------------- webserver integration


@pytest.fixture
def stack():
    from hivedscheduler_tpu.k8s.fake import FakeKubeClient
    from hivedscheduler_tpu.k8s.types import Node
    from hivedscheduler_tpu.runtime.scheduler import HivedScheduler
    from hivedscheduler_tpu.webserver import WebServer

    config = load_config(FIXTURE)
    config.web_server_address = "127.0.0.1:0"
    kube = FakeKubeClient()
    scheduler = HivedScheduler(config, kube)
    algo = scheduler.scheduler_algorithm
    for n in sorted({n for ccl in algo.full_cell_list.values()
                     for c in ccl[max(ccl)] for n in c.nodes}):
        kube.create_node(Node(name=n))
    scheduler.start()
    server = WebServer(scheduler)
    host, port = server.async_run()
    yield kube, scheduler, f"http://{host}:{port}"
    server.stop()


def get(base, path):
    import urllib.request

    with urllib.request.urlopen(base + path) as r:
        return r.status, json.loads(r.read())


class TestTracesEndpoint:
    def _schedule_some(self, kube, scheduler, n=3):
        from hivedscheduler_tpu.k8s import serde
        from hivedscheduler_tpu.runtime import extender as ei

        nodes = sorted(nd.name for nd in kube.list_nodes())
        for i in range(n):
            pod = make_pod(f"t{i}", {"virtualCluster": "vc2", "priority": 0,
                                     "chipType": "v5e-chip", "chipNumber": 8})
            kube.create_pod(pod)
            scheduler.filter_routine(ei.ExtenderArgs(
                pod=kube.get_pod(pod.namespace, pod.name), node_names=nodes))

    def test_traces_endpoint_serves_last_decisions(self, stack):
        kube, scheduler, base = stack
        obs_decisions.RECORDER.enable()
        self._schedule_some(kube, scheduler)
        status, body = get(base, C.TRACES_PATH)
        assert status == 200 and body["enabled"]
        assert len(body["items"]) == 3
        # most recent first, each with per-attempt outcome explanations
        assert body["items"][0]["pod"] == "t2(default/t2)"
        for item in body["items"]:
            assert item["outcome"] in ("bind", "wait")
            assert all({"where", "path", "outcome", "reason"} <= set(a)
                       for a in item["attempts"])
        status, body = get(base, C.TRACES_PATH + "?n=1")
        assert status == 200 and len(body["items"]) == 1
        assert body["items"][0]["pod"] == "t2(default/t2)"

    def test_chrome_endpoint_is_valid_trace(self, stack):
        kube, scheduler, base = stack
        obs_trace.enable()
        obs_decisions.RECORDER.enable()
        self._schedule_some(kube, scheduler)
        status, body = get(base, C.TRACES_CHROME_PATH)
        assert status == 200
        events = validate_chrome_trace(body)
        names = {e["name"] for e in events}
        assert "filter_routine" in names  # extender span
        assert any(n.startswith("schedule ") for n in names)  # decisions

    def test_traces_listed_in_index(self, stack):
        _, _, base = stack
        status, body = get(base, "/v1")
        assert status == 200
        assert C.TRACES_PATH in body["paths"]
        assert C.TRACES_CHROME_PATH in body["paths"]


class TestDemoCliTraceFlags:
    def test_cli_explain_and_trace_file(self, tmp_path, monkeypatch, capsys):
        """--fake-cluster --explain --trace-file: the demo run produces a
        Perfetto-loadable trace JSON on shutdown (acceptance criterion)."""
        import threading as _threading

        from hivedscheduler_tpu import cli
        from hivedscheduler_tpu.common import utils as common

        trace_file = tmp_path / "demo.trace.json"
        # ephemeral port: the fixture defaults to :30096, which a test must
        # not squat on
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(open(FIXTURE).read()
                            + '\nwebServerAddress: "127.0.0.1:0"\n')
        # release the CLI's stop.wait() immediately after startup
        stop = _threading.Event()

        def fake_stop_event():
            _threading.Timer(0.3, stop.set).start()
            return stop

        monkeypatch.setattr(common, "new_stop_event", fake_stop_event)
        rc = cli.main(["--config", str(cfg_path), "--fake-cluster",
                       "--explain", "--trace-file", str(trace_file)])
        assert rc == 0
        obj = json.loads(trace_file.read_text())
        validate_chrome_trace(obj)
        assert obs_decisions.RECORDER.enabled  # --fake-cluster run enables
