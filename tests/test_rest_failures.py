"""REST client failure ladder, driven through the local MiniApiServer stub
(no network): transient 5xx/429 retry with backoff, terminal 4xx surfacing
immediately, the 410 Gone relist+reconcile resync (synthesized deletes for
objects vanished during the watch gap), and ``watches_alive`` flipping false
on a wedged watch (connection refused past the failure threshold) then
recovering once the ApiServer returns on the same address."""

import threading
import time

import pytest
import urllib.error

from hivedscheduler_tpu.k8s.rest import RestKubeClient
from hivedscheduler_tpu.runtime.metrics import REGISTRY

from test_rest_client import MiniApiServer, wait_for


@pytest.fixture
def apiserver():
    s = MiniApiServer()
    yield s
    s.close()


def fast_client(url, **kw):
    """A client whose retry/backoff ladder runs at test speed."""
    kw.setdefault("max_retries", 3)
    kw.setdefault("retry_backoff_s", 0.01)
    kw.setdefault("retry_backoff_cap_s", 0.02)
    kw.setdefault("watch_backoff_s", 0.02)
    kw.setdefault("watch_backoff_cap_s", 0.05)
    kw.setdefault("watch_failure_threshold", 2)
    return RestKubeClient(url, **kw)


def _retries(op, reason) -> float:
    return REGISTRY._counters.get(
        ("tpu_hive_k8s_retries_total",
         (("op", op), ("reason", reason))), 0.0
    )


def test_transient_errors_retried_and_counted(apiserver):
    """500 then 429 on the list: the request ladder absorbs both, the call
    succeeds, and each retry lands in tpu_hive_k8s_retries_total."""
    apiserver.add_node("n0")
    before_500 = _retries("GET", "500")
    before_429 = _retries("GET", "429")
    apiserver.fail_next["/api/v1/nodes"] = [500, 429]
    client = fast_client(apiserver.url)
    assert [n.name for n in client.list_nodes()] == ["n0"]
    assert _retries("GET", "500") == before_500 + 1
    assert _retries("GET", "429") == before_429 + 1
    client.stop()


def test_transient_bind_retried(apiserver):
    """The Bind POST rides the same ladder (binds are idempotent: same pod,
    same node, same annotation merge)."""
    from hivedscheduler_tpu.k8s.types import Binding

    apiserver.add_node("n0")
    apiserver.add_pod("default", "p1")
    path = "/api/v1/namespaces/default/pods/p1/binding"
    apiserver.fail_next[path] = [503]
    client = fast_client(apiserver.url)
    client.bind_pod(Binding(pod_name="p1", pod_namespace="default",
                            pod_uid="p1", node="n0"))
    bound = client.get_pod("default", "p1")
    assert bound.node_name == "n0"
    client.stop()


def test_terminal_4xx_not_retried(apiserver):
    """A real rejection (403) surfaces immediately — only one wire request,
    no backoff burned."""
    apiserver.fail_next["/api/v1/nodes"] = [403, 403, 403, 403]
    client = fast_client(apiserver.url)
    with pytest.raises(urllib.error.HTTPError):
        client.list_nodes()
    with apiserver.lock:
        n_reqs = sum(1 for m, p in apiserver.requests
                     if m == "GET" and p == "/api/v1/nodes")
    assert n_reqs == 1
    client.stop()


def test_retry_exhaustion_raises(apiserver):
    """max_retries bounds the ladder: a persistently-500 endpoint fails
    after 1 + max_retries attempts."""
    apiserver.fail_next["/api/v1/pods"] = [500] * 10
    client = fast_client(apiserver.url, max_retries=2)
    with pytest.raises(urllib.error.HTTPError):
        client.list_pods()
    with apiserver.lock:
        n_reqs = sum(1 for m, p in apiserver.requests
                     if m == "GET" and p == "/api/v1/pods")
    assert n_reqs == 3  # initial + 2 retries
    client.stop()


def test_410_gone_resync_reconciles(apiserver):
    """The watch-gap ladder: objects created AND deleted while the watch
    was broken must surface as synthesized add/delete events after the 410
    Gone relist (the client's cache diff — reference informer semantics)."""
    apiserver.add_pod("default", "old")
    client = fast_client(apiserver.url)
    seen = {"adds": [], "deletes": []}
    client.on_pod_event(
        lambda p: seen["adds"].append(p.key),
        lambda o, p: None,
        lambda p: seen["deletes"].append(p.key),
    )
    client.on_node_event(lambda n: None, lambda o, n: None, lambda n: None)
    client.sync()
    assert seen["adds"] == ["default/old"]
    assert wait_for(lambda: len(apiserver.watchers) == 2)

    # the watch gap: one pod vanishes, another appears, NO events emitted
    with apiserver.lock:
        del apiserver.pods["default/old"]
        apiserver.rv += 1
        apiserver.pods["default/new"] = {
            "metadata": {"name": "new", "namespace": "default", "uid": "new",
                         "resourceVersion": str(apiserver.rv)},
            "spec": {"containers": []},
            "status": {"phase": "Pending"},
        }
    # ...then the ApiServer declares the client's resourceVersion Gone
    apiserver.emit("pods", {"type": "ERROR", "object": {"code": 410}})
    assert wait_for(lambda: "default/new" in seen["adds"])
    assert wait_for(lambda: "default/old" in seen["deletes"])
    client.stop()


def test_watches_alive_flips_and_recovers():
    """Kill the ApiServer: after watch_failure_threshold consecutive
    connection-refused reconnects the client reports watches_alive()=False
    (the scheduler's /healthz would go unhealthy). Restart the server on
    the same port: the watch reconnects and liveness recovers — no client
    restart needed."""
    server = MiniApiServer()
    port = server.httpd.server_address[1]
    server.add_node("n0")
    client = fast_client(server.url)
    seen = []
    client.on_node_event(lambda n: seen.append(n.name),
                         lambda o, n: seen.append(n.name), lambda n: None)
    client.on_pod_event(lambda p: None, lambda o, p: None, lambda p: None)
    client.sync()
    assert client.watches_alive()
    assert wait_for(lambda: len(server.watchers) == 2)

    server.close()  # connection refused from here on
    assert wait_for(lambda: not client.watches_alive(), timeout=10.0), (
        "watches_alive never flipped false after the ApiServer died"
    )

    # ApiServer comes back on the same address with one more node
    server2 = MiniApiServer(port=port)
    try:
        server2.add_node("n0")
        server2.add_node("n1")
        assert wait_for(lambda: client.watches_alive(), timeout=10.0), (
            "watches_alive never recovered after the ApiServer returned"
        )
        # ...and the reconnected watch delivers again
        assert wait_for(lambda: len(server2.watchers) >= 2, timeout=10.0)
        server2.add_node("n2")
        assert wait_for(lambda: "n2" in seen, timeout=10.0)
    finally:
        client.stop()
        server2.close()
