"""Guard for the live-placement handoff (HivedAlgorithm.add_allocated_pod).

The optimistic add may reuse the placement objects Schedule just computed
instead of re-deriving them from the bind annotation. These tests pin the
equivalence: a sequence run with the handoff enabled must produce exactly the
same group state (physical AND virtual placements, by cell address) as the
same sequence with the handoff disabled, and the handoff must disarm when
anything happens between Schedule and Add.
"""

import logging
import os
import random

import pytest

from helpers import all_node_names, make_pod, set_healthy_nodes

from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.k8s.types import Node
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

logging.getLogger().setLevel(logging.ERROR)

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)

SEQUENCE = [
    ("a", {"virtualCluster": "vc2", "priority": 5, "chipType": "v5p-chip",
           "chipNumber": 4,
           "affinityGroup": {"name": "ga",
                             "members": [{"podNumber": 2, "chipNumber": 4}]}}, 2),
    ("b", {"virtualCluster": "vc2", "priority": 0, "chipType": "v5e-chip",
           "chipNumber": 8}, 1),
    ("d", {"virtualCluster": "vc1", "priority": 2, "pinnedCellId": "pin1",
           "chipNumber": 4}, 1),
    ("c", {"virtualCluster": "vc1", "priority": -1, "chipType": "v5p-chip",
           "chipNumber": 4,
           "affinityGroup": {"name": "gc",
                             "members": [{"podNumber": 2, "chipNumber": 4}]}}, 2),
]


def run_sequence(disable_handoff):
    random.seed(0)
    h = HivedAlgorithm(load_config(FIXTURE))
    nodes = set_healthy_nodes(h)
    for name, spec, pods in SEQUENCE:
        for i in range(pods):
            pod = make_pod(f"{name}-{i}", spec)
            r = h.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, (name, r.pod_wait_info)
            if disable_handoff:
                h._live_stash = None
            h.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
    return h


def group_state(h):
    out = {}
    for g in h.affinity_groups.values():
        phys = {
            ln: [[c.address if c is not None else None for c in podp]
                 for podp in podps]
            for ln, podps in g.physical_leaf_cell_placement.items()
        }
        virt = None
        if g.virtual_leaf_cell_placement is not None:
            virt = {
                ln: [[c.address if c is not None else None for c in podp]
                     for podp in podps]
                for ln, podps in g.virtual_leaf_cell_placement.items()
            }
        out[g.name] = (g.state, phys, virt)
    return out


def free_state(h):
    return {
        (chain, lv): sorted(c.address for c in ccl[lv])
        for chain, ccl in h.free_cell_list.items()
        for lv in sorted(ccl)
    }


def test_live_placement_equivalence():
    fast = run_sequence(disable_handoff=False)
    slow = run_sequence(disable_handoff=True)
    assert group_state(fast) == group_state(slow)
    assert free_state(fast) == free_state(slow)
    # virtual bindings must agree too (which physical cells carry which
    # virtual cells)
    def bindings(h):
        return {
            (chain, c.address): c.virtual_cell.address
            for chain, ccl in h.full_cell_list.items()
            for lv in ccl
            for c in ccl[lv]
            if c.virtual_cell is not None
        }
    assert bindings(fast) == bindings(slow)


def test_inlined_usage_walk_matches_canonical_method():
    """cell_allocation.update_used_leaf_cell_num_at_priority inlines the
    zero-popping dict update of Cell.increase_used_leaf_cell_num_at_priority
    for speed; this guard pins the copies together behaviorally across
    positive, negative and zero-crossing deltas."""
    from hivedscheduler_tpu.algorithm.cell import PhysicalCell
    from hivedscheduler_tpu.algorithm.cell_allocation import (
        update_used_leaf_cell_num_at_priority,
    )

    def chain():
        cells = [
            PhysicalCell(chain="c", level=lv, at_or_higher_than_node=True,
                         total_leaf_cell_num=1, cell_type="t", address=str(lv),
                         is_node_level=lv == 1)
            for lv in (1, 2, 3)
        ]
        cells[0].parent = cells[1]
        cells[1].parent = cells[2]
        return cells

    walked, canonical = chain(), chain()
    deltas = [(5, True), (5, True), (7, True), (7, False), (5, False)]
    for p, inc in deltas:
        update_used_leaf_cell_num_at_priority(walked[0], p, inc)
        c = canonical[0]
        while c is not None:
            c.increase_used_leaf_cell_num_at_priority(p, 1 if inc else -1)
            c = c.parent
    for w, k in zip(walked, canonical):
        assert w.used_leaf_cell_num_at_priorities == k.used_leaf_cell_num_at_priorities
        # zero entries must be POPPED, not stored as 0 (the opportunistic
        # packing sort iterates this dict)
        assert 7 not in w.used_leaf_cell_num_at_priorities


def test_handoff_disarms_on_interleaved_mutation():
    """A node event between Schedule and Add must invalidate the stash; the
    annotation-driven path then runs (and still succeeds)."""
    random.seed(0)
    h = HivedAlgorithm(load_config(FIXTURE))
    nodes = set_healthy_nodes(h)
    pod = make_pod("x", {"virtualCluster": "vc2", "priority": 5,
                         "chipType": "v5p-chip", "chipNumber": 4})
    r = h.schedule(pod, nodes, FILTERING_PHASE)
    assert r.pod_bind_info is not None
    assert h._live_stash is not None
    # interleaved mutation: a node health event
    h.add_node(Node(name=nodes[0]))
    h.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
    g = h.get_affinity_group("default/x")
    assert g.status.state == "Allocated"


def test_handoff_disarms_on_stale_annotation():
    """An annotation whose gang fragment differs from the stashed one (e.g.
    a bind retry of an older decision) must not use the live placement."""
    random.seed(0)
    h = HivedAlgorithm(load_config(FIXTURE))
    nodes = set_healthy_nodes(h)
    pod = make_pod("y", {"virtualCluster": "vc2", "priority": 5,
                         "chipType": "v5p-chip", "chipNumber": 4})
    r = h.schedule(pod, nodes, FILTERING_PHASE)
    bp = new_binding_pod(pod, r.pod_bind_info)
    # corrupt the stash fragment: the byte-compare must reject it
    seq, name, frag, gp, gv = h._live_stash
    h._live_stash = (seq, name, frag + " ", gp, gv)
    h.add_allocated_pod(bp)
    g = h.get_affinity_group("default/y")
    assert g.status.state == "Allocated"
