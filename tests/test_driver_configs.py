"""The five driver scenarios from BASELINE.json, verbatim:

1. 1-leaf-cell AffinityGroup on a single-node physicalCluster
2. 8-chip gang job on one v5e-8 host cell
3. Multi-VC guaranteed + opportunistic jobs on v5p-64 with inter-VC preemption
4. Contiguous 4x4x4 ICI-mesh slice request on v5p-256 (topology-aware buddy alloc)
5. Mixed v4/v5e SKU-type cells with pinned cells + bad-hardware-aware rescheduling
"""

import logging
import os

import pytest

from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.api.config import Config, load_config, new_config
from hivedscheduler_tpu.api.types import (
    CellTypeSpec,
    MeshLevelSpec,
    MeshSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    PinnedCellSpec,
    VirtualCellSpec,
    VirtualClusterSpec,
)
from helpers import make_pod, set_healthy_nodes

from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.k8s.types import Node
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE, PREEMPTING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

logging.getLogger().setLevel(logging.ERROR)

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)




def allocate(h, pod, nodes, phase=FILTERING_PHASE):
    r = h.schedule(pod, nodes, phase)
    assert r.pod_bind_info is not None, (r.pod_wait_info, r.pod_preempt_info)
    bp = new_binding_pod(pod, r.pod_bind_info)
    h.add_allocated_pod(bp)
    return bp, r.pod_bind_info


def test_config1_single_leaf_cell_on_single_node_cluster():
    cfg = new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={
                "node": CellTypeSpec(child_cell_type="chip", child_cell_number=4,
                                     is_node_level=True),
            },
            physical_cells=[PhysicalCellSpec(cell_type="node", cell_address="n0")],
        ),
        virtual_clusters={"vc": VirtualClusterSpec(
            virtual_cells=[VirtualCellSpec(cell_number=1, cell_type="node")])},
    ))
    h = HivedAlgorithm(cfg)
    nodes = set_healthy_nodes(h)
    _, info = allocate(h, make_pod("p", {
        "virtualCluster": "vc", "priority": 0, "leafCellNumber": 1}), nodes)
    assert info.node == "n0" and len(info.leaf_cell_isolation) == 1


def test_config2_v5e8_gang_on_one_host():
    h = HivedAlgorithm(load_config(FIXTURE))
    nodes = set_healthy_nodes(h)
    _, info = allocate(h, make_pod("g", {
        "virtualCluster": "vc2", "priority": 0,
        "chipType": "v5e-chip", "chipNumber": 8}), nodes)
    assert info.node == "v5e-host0/0-0"
    assert sorted(info.leaf_cell_isolation) == list(range(8))


def test_config3_multi_vc_inter_vc_preemption_on_v5p64():
    h = HivedAlgorithm(load_config(FIXTURE))
    nodes = set_healthy_nodes(h)
    # opportunistic jobs from vc2 spill across the whole v5p-64
    opp = []
    for i in range(16):
        bp, _ = allocate(h, make_pod(f"opp-{i}", {
            "virtualCluster": "vc2", "priority": -1,
            "chipType": "v5p-chip", "chipNumber": 4}), nodes)
        opp.append(bp)
    # vc1's guaranteed gang reclaims its share by preempting OT pods
    spec = {"virtualCluster": "vc1", "priority": 10, "chipType": "v5p-chip",
            "chipNumber": 4,
            "affinityGroup": {"name": "g", "members": [{"podNumber": 8,
                                                        "chipNumber": 4}]}}
    r = h.schedule(make_pod("g-0", spec), nodes, PREEMPTING_PHASE)
    assert r.pod_preempt_info is not None
    victims = {v.uid for v in r.pod_preempt_info.victim_pods}
    assert victims <= {bp.uid for bp in opp}


def test_config4_contiguous_4x4x4_on_v5p256():
    mesh = MeshSpec(
        topology=(8, 8, 4), chip_type="v5p-chip", host_shape=(2, 2, 1),
        levels=[MeshLevelSpec("v5p-2x2x2", (2, 2, 2)),
                MeshLevelSpec("v5p-4x4x2", (4, 4, 2)),
                MeshLevelSpec("v5p-4x4x4", (4, 4, 4)),
                MeshLevelSpec("v5p-8x4x4", (8, 4, 4))],
    )
    cfg = new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={"v5p-256": CellTypeSpec(mesh=mesh)},
            physical_cells=[PhysicalCellSpec(cell_type="v5p-256",
                                             cell_address="pod0")],
        ),
        virtual_clusters={"vc": VirtualClusterSpec(
            virtual_cells=[VirtualCellSpec(cell_number=4,
                                           cell_type="v5p-256.v5p-4x4x4")])},
    ))
    h = HivedAlgorithm(cfg)
    nodes = set_healthy_nodes(h)
    spec = {"virtualCluster": "vc", "priority": 0, "chipType": "v5p-chip",
            "chipNumber": 4,
            "affinityGroup": {"name": "cube",
                              "members": [{"podNumber": 16, "chipNumber": 4}]}}
    origins = []
    for i in range(16):
        _, info = allocate(h, make_pod(f"cube-{i}", spec), nodes)
        origins.append(tuple(int(x) for x in info.node.split("/")[-1].split("-")))
    # the 16 hosts (2x2x1 each) must tile exactly one aligned 4x4x4 sub-mesh
    xs = {o[0] for o in origins}
    ys = {o[1] for o in origins}
    zs = {o[2] for o in origins}
    assert len(set(origins)) == 16
    assert len(xs) == 2 and max(xs) - min(xs) == 2 and min(xs) % 4 == 0
    assert len(ys) == 2 and max(ys) - min(ys) == 2 and min(ys) % 4 == 0
    assert len(zs) == 4 and min(zs) == 0  # full z extent of the 4-deep mesh


def test_config5_mixed_sku_pinned_and_bad_hardware_rescheduling():
    h = HivedAlgorithm(load_config(FIXTURE))  # v4 + v5p + v5e chains, pin1
    nodes = set_healthy_nodes(h)
    # mixed SKU: one pod per chip type without specifying, one with
    _, info_v4 = allocate(h, make_pod("a", {
        "virtualCluster": "vc1", "priority": 0,
        "chipType": "v4-chip", "chipNumber": 8}), nodes)
    assert info_v4.cell_chain == "v4-node-pool"
    # pinned cell usage
    _, info_pin = allocate(h, make_pod("b", {
        "virtualCluster": "vc1", "priority": 2, "pinnedCellId": "pin1",
        "chipNumber": 4}), nodes)
    assert info_pin.node.startswith("v5p-pod0/0-0-")
    # bad hardware: the first v4 node dies; a new pod reschedules elsewhere
    h.delete_node(Node(name=info_v4.node))
    _, info_v4b = allocate(h, make_pod("c", {
        "virtualCluster": "vc1", "priority": 0,
        "chipType": "v4-chip", "chipNumber": 8}), nodes)
    assert info_v4b.node != info_v4.node
    # and the bad node is visible in the cluster status
    status = h.get_physical_cluster_status()
    flat = []

    def walk(s):
        flat.append(s)
        for c in s.cell_children:
            walk(c)

    for s in status:
        walk(s)
    assert any(s.cell_healthiness == api.CELL_BAD for s in flat)
