"""Inspect API detail tests: phys<->virt cross-links, pinned cells, mesh
geometry exposure, and the '-opp' pseudo-cells (reference inspect semantics:
api/types.go:184-273, utils.go:419-452)."""

import logging
import os

from helpers import make_pod, set_healthy_nodes, walk_status

from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

logging.getLogger().setLevel(logging.ERROR)

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)


def fresh():
    h = HivedAlgorithm(load_config(FIXTURE))
    nodes = set_healthy_nodes(h)
    return h, nodes


def test_cross_links_after_allocation():
    h, nodes = fresh()
    pod = make_pod("p", {"virtualCluster": "vc2", "priority": 3,
                         "chipType": "v5e-chip", "chipNumber": 8})
    r = h.schedule(pod, nodes, FILTERING_PHASE)
    h.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))

    vc2 = h.get_virtual_cluster_status("vc2")
    bound = [s for s in walk_status(vc2) if s.physical_cell is not None]
    assert bound, "allocated virtual cells must expose their physical peer"
    top = next(s for s in bound if s.cell_type == "v5e-8")
    assert top.physical_cell.cell_address == "v5e-host0/0-0"
    assert top.cell_priority == 3 and top.cell_state == "Used"
    # physical side mirrors back
    pc = h.get_physical_cluster_status()
    phys = [s for s in walk_status(pc) if s.virtual_cell is not None]
    assert any(s.vc == "vc2" for s in phys)


def test_mesh_geometry_exposed():
    h, _ = fresh()
    pc = h.get_physical_cluster_status()
    v5p = next(s for s in pc if s.cell_type == "v5p-64")
    assert v5p.mesh_shape == (4, 4, 4) and v5p.mesh_origin == (0, 0, 0)
    d = v5p.to_dict()
    assert d["meshShape"] == [4, 4, 4]
    child_shapes = {tuple(c.mesh_shape) for c in v5p.cell_children}
    assert child_shapes == {(4, 4, 2)}


def test_opp_pseudo_cells_lifecycle():
    h, nodes = fresh()
    pod = make_pod("o", {"virtualCluster": "vc1", "priority": -1,
                         "chipType": "v5p-chip", "chipNumber": 4})
    r = h.schedule(pod, nodes, FILTERING_PHASE)
    bp = new_binding_pod(pod, r.pod_bind_info)
    h.add_allocated_pod(bp)
    vc1 = h.get_virtual_cluster_status("vc1")
    opp = [s for s in vc1 if s.cell_address.endswith("-opp")]
    assert len(opp) == 4  # one pseudo-cell per opportunistic chip
    assert all(s.cell_priority == -1 and s.physical_cell is not None for s in opp)
    h.delete_allocated_pod(bp)
    vc1 = h.get_virtual_cluster_status("vc1")
    assert not [s for s in vc1 if s.cell_address.endswith("-opp")]


def test_pinned_cell_statically_bound():
    h, _ = fresh()
    vc1 = h.get_virtual_cluster_status("vc1")
    pinned = [s for s in walk_status(vc1)
              if s.physical_cell is not None and s.cell_type == "v5p-2x2x2"]
    assert pinned, "the pinned cell is bound at startup"
    assert pinned[0].physical_cell.cell_address == "v5p-pod0/s0-0-0"


def test_affinity_group_status_fields():
    h, nodes = fresh()
    spec = {"virtualCluster": "vc2", "priority": 1, "chipType": "v5p-chip",
            "chipNumber": 4,
            "affinityGroup": {"name": "g", "members": [{"podNumber": 2,
                                                        "chipNumber": 4}]}}
    for i in range(2):
        p = make_pod(f"g-{i}", spec)
        r = h.schedule(p, nodes, FILTERING_PHASE)
        h.add_allocated_pod(new_binding_pod(p, r.pod_bind_info))
    g = h.get_affinity_group("g")
    d = g.to_dict()
    assert d["metadata"]["name"] == "g"
    assert d["status"]["state"] == "Allocated"
    assert len(d["status"]["allocatedPods"]) == 2
    # physicalPlacement: node -> chip indices; virtualPlacement: preassigned -> leaves
    assert sum(len(v) for v in d["status"]["physicalPlacement"].values()) == 8
    assert sum(len(v) for v in d["status"]["virtualPlacement"].values()) == 8
