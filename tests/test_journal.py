"""Gang-lifecycle flight recorder (ISSUE 11): journal core semantics
(causal chaining, wait-attribution intervals, bounded ring, crash-safe
spool), the schedule-ladder / defrag / elastic emitters, the
/v1/inspect/gangs endpoints causally reconstructing a complete defrag
migration and an elastic shrink->grow episode, the Perfetto merge, the
chaos invariant, and the overhead gate (disabled path = one bool check;
enabled cost bounded).
"""

import json
import os
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from helpers import validate_chrome_trace  # noqa: E402

from tests.test_defrag import make_pod, mini_config  # noqa: E402,F401
from tests.test_defrag_runtime import (  # noqa: E402
    build_scheduler,
    drive,
    fragmented_scheduler,
)
from tests.test_elastic_runtime import (  # noqa: E402
    blocked_elastic_scheduler,
)

from hivedscheduler_tpu.api import constants as C  # noqa: E402
from hivedscheduler_tpu.chaos import invariants  # noqa: E402
from hivedscheduler_tpu.obs import journal  # noqa: E402
from hivedscheduler_tpu.obs import trace as obs_trace  # noqa: E402


@pytest.fixture(autouse=True)
def _journal_isolation():
    """Every test starts with the journal off and empty; the global
    singleton never leaks across tests."""
    journal.disable()
    journal.JOURNAL.clear()
    obs_trace.disable()
    obs_trace.TRACER.clear()
    yield
    journal.disable()
    journal.JOURNAL.clear()
    obs_trace.disable()
    obs_trace.TRACER.clear()


# ----------------------------------------------------------------- core


class TestJournalCore:
    def test_disabled_is_noop(self):
        assert journal.emit("bind", "g") is None
        assert journal.note_wait("g", "vc_quota") is None
        assert journal.note_phase("g", "running", "bind") is None
        assert len(journal.JOURNAL) == 0 and journal.JOURNAL.gangs() == []

    def test_unregistered_event_type_rejected(self):
        journal.enable()
        with pytest.raises(ValueError,
                           match="not a registered journal event type"):
            journal.emit("made_up_event", "g")

    def test_unregistered_bucket_rejected(self):
        journal.enable()
        with pytest.raises(
                ValueError,
                match="not a registered wait-attribution bucket"):
            journal.note_wait("g", "made_up_bucket")

    def test_causal_auto_chain_and_explicit_cross_gang_cause(self):
        journal.enable()
        a = journal.note_wait("w", "fragmentation")
        b = journal.emit("defrag_planned", "w")  # auto-chains to a
        c = journal.emit("migration_evict", "mover", cause=b)  # cross-gang
        events = {e.id: e for e in journal.JOURNAL.snapshot()}
        assert events[b].cause == a
        assert events[c].cause == b and events[c].gang == "mover"

    def test_wait_transition_closes_interval_and_observes(self):
        from hivedscheduler_tpu.runtime.metrics import REGISTRY

        journal.enable()
        journal.note_wait("g", "vc_quota", at=10.0)
        # same bucket: no new event, the interval continues
        assert journal.note_wait("g", "vc_quota", at=11.0) is not None
        assert len(journal.JOURNAL) == 1
        journal.note_wait("g", "fragmentation", at=13.0)
        journal.note_phase("g", "running", "bind", at=17.0)
        totals = journal.JOURNAL.wait_totals()
        assert totals == {"vc_quota": 3.0, "fragmentation": 4.0}
        ivs = sorted(journal.JOURNAL.wait_intervals())
        assert ivs == [("g", "fragmentation", 13.0, 17.0),
                       ("g", "vc_quota", 10.0, 13.0)]
        text = REGISTRY.render()
        assert 'tpu_hive_gang_wait_seconds_bucket{reason="vc_quota"' in text

    def test_note_phase_idempotent_per_incarnation(self):
        journal.enable()
        journal.note_phase("g", "running", "bind")
        journal.note_phase("g", "running", "bind")  # second member pod
        assert [e.type for e in journal.JOURNAL.snapshot()] == ["bind"]
        journal.note_phase("g", "closed", "released")
        # release of a gang the journal never opened: no orphan close
        journal.note_phase("ghost", "closed", "released")
        assert [e.type for e in journal.JOURNAL.snapshot()] == [
            "bind", "released"]

    def test_ring_bounded(self):
        j = journal.Journal(capacity=8, metrics=False)
        j.enabled = True
        for i in range(20):
            j.emit("bind", f"g{i}")
        assert len(j) == 8 and j.evicted == 12

    def test_spool_is_replayable_jsonl(self, tmp_path):
        spool = str(tmp_path / "journal.jsonl")
        journal.enable(spool_path=spool)
        journal.note_wait("g", "vc_quota")
        journal.note_phase("g", "running", "bind")
        journal.disable()
        lines = [json.loads(ln) for ln in open(spool)]
        assert [ln["type"] for ln in lines] == ["queued", "bind"]
        assert lines[0]["bucket"] == "vc_quota"
        assert lines[1]["cause"] == lines[0]["id"]

    def test_schema_and_buckets_documented(self):
        assert all(doc for doc in journal.SCHEMA.values())
        for bucket in ("vc_quota", "fragmentation", "bad_hardware",
                       "reservation_hold", "priority", "elastic_degraded"):
            assert bucket in journal.WAIT_BUCKETS

    def test_classifier_maps_ladder_reasons(self):
        cw = journal.classify_wait
        assert cw("insufficient capacity when scheduling in VC x") == \
            "fragmentation"
        assert cw("insufficient free cell in the VC at the preassigned "
                  "level (2) when scheduling in VC x") == "vc_quota"
        assert cw("have to use at least one bad node n1") == "bad_hardware"
        assert cw("placement overlaps cells held by a defrag "
                  "reservation") == "reservation_hold"
        assert cw("") == "unknown" and cw("whatever else") == "unknown"


# -------------------------------------------------- chrome-trace merge


class TestPerfettoMerge:
    def test_journal_lanes_merge_into_chrome_export(self):
        obs_trace.enable()
        journal.enable()
        journal.note_wait("w", "vc_quota")
        journal.note_phase("w", "running", "bind")
        trace_obj = obs_trace.to_chrome_trace()
        events = validate_chrome_trace(trace_obj)
        names = [e["name"] for e in events]
        assert "queued" in names and "bind" in names
        assert "wait:vc_quota" in names  # the closed interval as an X span
        lanes = [e for e in events if e["ph"] == "M"
                 and e["args"].get("name") == "gang w"]
        assert lanes, "each gang must get a named Perfetto lane"

    def test_disabled_journal_leaves_export_unchanged(self):
        obs_trace.enable()
        before = obs_trace.to_chrome_trace()["traceEvents"]
        journal.JOURNAL.clear()
        after = obs_trace.to_chrome_trace()["traceEvents"]
        assert [e["name"] for e in before] == [e["name"] for e in after]


# ------------------------------------------- schedule-ladder emitters


class TestScheduleLadderJournal:
    def test_bind_wait_release_lifecycle(self):
        journal.enable()
        sched, kube, nodes = build_scheduler()
        assert drive(sched, kube, nodes, make_pod("g1-0", "g1", 4)) is not None
        tl = journal.JOURNAL.timeline("g1")
        assert [e["type"] for e in tl["events"]] == ["bind"]
        # an 8-chip gang cannot fit beside g1: queued with a classified
        # bucket
        assert drive(sched, kube, nodes,
                     make_pod("g2-0", "g2", 4, pods=2)) is None
        tl2 = journal.JOURNAL.timeline("g2")
        assert [e["type"] for e in tl2["events"]] == ["queued"]
        assert tl2["events"][0]["bucket"] in journal.WAIT_BUCKETS
        assert tl2["summary"]["openWait"] is not None
        # completion releases
        kube.delete_pod("default", "g1-0")
        tl = journal.JOURNAL.timeline("g1")
        assert [e["type"] for e in tl["events"]] == ["bind", "released"]
        assert tl["summary"]["phase"] == "closed"

    def test_gangs_summary_served(self):
        journal.enable()
        sched, kube, nodes = build_scheduler()
        drive(sched, kube, nodes, make_pod("g1-0", "g1", 4))
        items = journal.JOURNAL.gangs()
        assert [g["gang"] for g in items] == ["g1"]
        assert items[0]["phase"] == "running"


# --------------------------------- causal reconstruction over HTTP


def _serve(sched):
    from hivedscheduler_tpu.webserver import WebServer

    server = WebServer(sched, address="127.0.0.1:0")
    host, port = server.async_run()
    return server, f"http://{host}:{port}"


def _get(base, path):
    with urllib.request.urlopen(base + path) as r:
        return r.status, json.loads(r.read())


class TestTimelineReconstruction:
    def test_defrag_migration_is_causally_complete(self):
        """/v1/inspect/gangs/<id>/timeline reconstructs the whole
        migration: queued -> defrag_planned(cause=queued) -> the mover's
        evict/rebind chained to the plan -> migration_done -> bind."""
        journal.enable()
        sched, kube, nodes = fragmented_scheduler()
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        sched.resume_migrations()
        assert drive(sched, kube, nodes, w) is not None
        server, base = _serve(sched)
        try:
            status, gangs = _get(base, C.GANGS_PATH)
            assert status == 200 and gangs["enabled"]
            assert {g["gang"] for g in gangs["items"]} >= {"w"}
            status, tl = _get(base, C.GANGS_PATH + "/w/timeline")
        finally:
            server.stop()
        ev = {e["type"]: e for e in tl["events"]}
        assert ["queued", "defrag_planned", "migration_done", "bind"] == [
            e["type"] for e in tl["events"]]
        assert ev["defrag_planned"]["cause"] == ev["queued"]["id"]
        assert ev["migration_done"]["cause"] == ev["defrag_planned"]["id"]
        assert ev["bind"]["cause"] == ev["migration_done"]["id"]
        # the mover's eviction/rebind chain off the waiter's plan event
        mover = plan["moves"][0]["group"]
        mtl = journal.JOURNAL.timeline(mover)
        mtypes = [e["type"] for e in mtl["events"]]
        assert mtypes == ["bind", "migration_evict", "released", "bind",
                          "migration_rebound"]
        mev = {e["type"]: e for e in mtl["events"]}
        assert mev["migration_evict"]["cause"] == ev["defrag_planned"]["id"]
        assert mev["migration_rebound"]["cause"] == \
            ev["defrag_planned"]["id"]
        # the waiter's queue wait is closed and attributed
        assert tl["summary"]["openWait"] is None
        assert set(tl["summary"]["waits"]) <= set(journal.WAIT_BUCKETS)
        invariants.check_journal(ctx="post-migration")

    def test_elastic_shrink_grow_episode_is_causally_complete(self):
        """The shrink offer, degraded bind, elastic_degraded wait, grow
        plan and grow completion form one causal chain on gang e."""
        journal.enable()
        sched, kube, nodes = blocked_elastic_scheduler()
        assert sched.defrag_tick()["elasticOffer"] is not None
        kube.delete_pod("default", "g1-0")  # capacity frees
        grows = sched.defrag_tick()["elasticGrows"]
        assert grows and grows[0]["group"] == "e"
        rep = sched.resume_migrations()
        assert rep[grows[0]["migrationId"]]["state"] == "Done"
        tl = journal.JOURNAL.timeline("e")
        types = [e["type"] for e in tl["events"]]
        assert types == ["queued", "elastic_offer", "bind", "queued",
                         "elastic_grow_planned", "migration_evict",
                         "released", "bind", "migration_rebound",
                         "elastic_grow_done", "migration_done"]
        ev = {}
        for e in tl["events"]:
            ev.setdefault(e["type"], e)
        # the degraded wait is attributed to elastic_degraded and caused
        # by the shrink offer
        degraded_queued = tl["events"][3]
        assert degraded_queued["bucket"] == "elastic_degraded"
        assert degraded_queued["cause"] == ev["elastic_offer"]["id"]
        assert ev["migration_evict"]["cause"] == \
            ev["elastic_grow_planned"]["id"]
        assert ev["elastic_grow_done"]["cause"] == \
            ev["elastic_grow_planned"]["id"]
        # wait accounting: both the full-shape block and the degraded
        # window are closed intervals now
        waits = tl["summary"]["waits"]
        assert "elastic_degraded" in waits
        invariants.check_journal(ctx="post-grow")


# ------------------------------------------------------ chaos invariant


class TestCheckJournal:
    def test_noop_when_disabled(self):
        invariants.check_journal()  # must not raise

    def test_terminal_without_open_flagged(self):
        j = journal.Journal(metrics=False)
        j.enabled = True
        j.emit("released", "g")
        with pytest.raises(invariants.InvariantViolation,
                           match="no opening event"):
            invariants.check_journal(journal=j)

    def test_duplicate_terminal_flagged(self):
        j = journal.Journal(metrics=False)
        j.enabled = True
        j.emit("bind", "g")
        j.emit("released", "g")
        j.emit("released", "g")
        with pytest.raises(invariants.InvariantViolation,
                           match="duplicate terminal"):
            invariants.check_journal(journal=j)

    def test_non_backward_cause_flagged(self):
        j = journal.Journal(metrics=False)
        j.enabled = True
        j.emit("bind", "g", cause=99)
        with pytest.raises(invariants.InvariantViolation,
                           match="non-backward cause"):
            invariants.check_journal(journal=j)

    def test_orphan_cause_flagged(self):
        # a gap inside the retained id range (corrupted/hand-edited spool
        # replay): cause 2 is >= min retained id but missing
        j = journal.Journal(metrics=False)
        j.enabled = True
        j.emit("bind", "g")
        with j._lock:
            j._ring.append(journal.Event(id=3, gang="g", type="released",
                                         cause=2))
        with pytest.raises(invariants.InvariantViolation,
                           match="orphan cause"):
            invariants.check_journal(journal=j)

    def test_clean_episode_passes_and_reopen_is_legal(self):
        j = journal.Journal(metrics=False)
        j.enabled = True
        j.emit("queued", "g", bucket="fragmentation")
        j.emit("bind", "g")
        j.emit("released", "g")
        j.emit("bind", "g")  # migration re-incarnation
        j.emit("released", "g")
        invariants.check_journal(journal=j)


# -------------------------------------------------------- overhead gate


class TestOverheadGate:
    def test_disabled_path_takes_no_lock_and_allocates_nothing(self):
        """The PR 1 contract: disabled emit is ONE attribute check — it
        must return before ever touching the lock or the ring."""
        j = journal.JOURNAL
        saved = j._lock
        j._lock = None  # any lock acquisition would raise AttributeError
        try:
            for _ in range(1000):
                assert journal.emit("bind", "g") is None
                assert journal.note_wait("g", "vc_quota") is None
                assert journal.note_phase("g", "running", "bind") is None
        finally:
            j._lock = saved
        assert len(j) == 0

    def test_schedule_hot_path_emits_nothing_while_disabled(self):
        sched, kube, nodes = build_scheduler()
        drive(sched, kube, nodes, make_pod("g1-0", "g1", 4))
        assert len(journal.JOURNAL) == 0

    def test_enabled_bounded_ring_cost(self):
        """The enabled path is a dict update + deque append: pin a very
        generous absolute budget so a regression to O(gangs) or an
        unbounded structure fails loudly without being box-noise flaky."""
        j = journal.Journal(capacity=4096, metrics=False)
        j.enabled = True
        n = 20000
        t0 = time.perf_counter()
        for i in range(n):
            j.note_wait(f"g{i % 64}", "vc_quota" if i % 2 else
                        "fragmentation", at=float(i))
        dt = time.perf_counter() - t0
        assert len(j) <= 4096  # the ring stayed bounded
        assert dt < 5.0, f"{n} enabled emits took {dt:.2f}s"


# ------------------------------------------------------ CLI parse smoke


class TestCliFlags:
    def test_scheduler_cli_parses_journal_file(self):
        import subprocess

        proc = subprocess.run(
            [sys.executable, "-m", "hivedscheduler_tpu.cli", "--help"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0 and "--journal-file" in proc.stdout

    def test_serve_and_train_parse_journal_file(self, capsys):
        from hivedscheduler_tpu import serve, train

        for mod in (serve, train):
            with pytest.raises(SystemExit) as exc:
                mod.main(["--help"])
            assert exc.value.code == 0
            assert "--journal-file" in capsys.readouterr().out
