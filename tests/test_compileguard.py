"""HIVED_COMPILE_GUARD runtime recompile sanitizer (ISSUE 8).

The two load-bearing bounds, measured instead of promised:
- a fused-window serving engine (``decode_steps=K``) compiles at most
  ``log2(K) + 1`` distinct ``serve.decode_multi`` programs (the PR 5
  pow2-bucketing claim);
- a warmed engine re-running an identical workload compiles ZERO new
  programs across every guarded entry point — every steady-state
  serving/decode loop is a recompile detector under the guard.

Everything runs on the CPU backend with tiny models; the guard itself
(``common/compileguard.py``) is env-gated at wrap time, so engines are
constructed after the monkeypatch sets the flag."""

import math
import os
import sys

import numpy as np
import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from hivedscheduler_tpu.common import compileguard  # noqa: E402
from hivedscheduler_tpu.models import decode, serving, transformer as tm  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=2, n_kv_heads=2,
                n_layers=1, d_ff=64, max_seq_len=64, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture()
def guard(monkeypatch):
    monkeypatch.setenv("HIVED_COMPILE_GUARD", "1")
    compileguard.reset()
    yield
    compileguard.reset()


# ---------------------------------------------------------------------------
# unit behavior
# ---------------------------------------------------------------------------

def test_disabled_returns_raw_jit(monkeypatch):
    monkeypatch.delenv("HIVED_COMPILE_GUARD", raising=False)
    f = compileguard.jit(lambda x: x + 1)
    assert not isinstance(f, compileguard._CountingJit)
    assert not compileguard.enabled()


def test_counts_per_label_and_budget(guard):
    f = compileguard.jit(lambda x: x * 2, guard_label="t.double")
    f(jnp.ones(3))
    assert compileguard.counts() == {"t.double": 1}
    f(jnp.ones(3))  # cache hit
    assert compileguard.counts() == {"t.double": 1}
    f(jnp.ones(4))  # new shape -> new program
    assert compileguard.counts() == {"t.double": 2}
    assert compileguard.total() == 2

    with compileguard.budget(0):
        f(jnp.ones(4))  # warm: fine
    with pytest.raises(compileguard.RecompileError,
                       match="compile budget exceeded"):
        with compileguard.budget(0):
            f(jnp.ones(5))
    with compileguard.budget(1, label="t.double"):
        f(jnp.ones(6))
    compileguard.reset()
    assert compileguard.counts() == {}


def test_budget_noop_when_disabled(monkeypatch):
    monkeypatch.delenv("HIVED_COMPILE_GUARD", raising=False)
    with compileguard.budget(0):
        pass  # must not raise or probe anything


def test_static_args_count_as_variants(guard):
    f = compileguard.jit(lambda x, k: x[:k], guard_label="t.slice",
                        static_argnums=(1,))
    f(jnp.arange(8), 2)
    f(jnp.arange(8), 4)
    f(jnp.arange(8), 2)
    assert compileguard.counts()["t.slice"] == 2


# ---------------------------------------------------------------------------
# the fused-window bound: log2(K) + 1 decode_multi programs
# ---------------------------------------------------------------------------

def test_fused_window_compile_bound(guard, setup):
    cfg, params = setup
    K = 8
    eng = serving.ServingEngine(params, cfg, max_batch=1, max_len=64,
                                decode_steps=K, seed=3)
    eng.submit([5, 9, 2], 15)  # windows 8 -> 4 -> 2 -> 1
    eng.run_until_drained()
    c = compileguard.counts()
    bound = int(math.log2(K)) + 1
    assert 2 <= c.get("serve.decode_multi", 0) <= bound, c
    assert eng.fused_windows >= 3
    assert c.get("serve.prefill", 0) == 1


# ---------------------------------------------------------------------------
# steady state: a warmed engine compiles nothing
# ---------------------------------------------------------------------------

PROMPTS = [[5, 9, 2], [17, 3, 8], [1, 4, 7], [11, 2, 6]]
BUDGETS = [8, 8, 8, 8]


def _run_workload(eng):
    reqs = [eng.submit(list(p), n) for p, n in zip(PROMPTS, BUDGETS)]
    eng.run_until_drained()
    assert all(r.done for r in reqs)
    return [r.tokens_out for r in reqs]


def test_serving_steady_state_zero_recompiles(guard, setup):
    cfg, params = setup
    eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                decode_steps=4, seed=7)
    first = _run_workload(eng)  # warmup: compiles prefill/decode variants
    assert compileguard.total() > 0
    compileguard.reset()
    with compileguard.budget(0):
        second = _run_workload(eng)  # identical workload: fully warmed
    assert second == first  # same slots, same greedy streams
    assert compileguard.counts() == {}


@pytest.mark.slow  # tier-1 wall-time budget: the dense steady-state cousin stays tier-1
def test_paged_engine_steady_state_zero_recompiles(guard, setup):
    cfg, params = setup
    eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                decode_steps=4, seed=7, page_size=8)
    first = _run_workload(eng)
    compileguard.reset()
    with compileguard.budget(0):
        second = _run_workload(eng)
    assert second == first


def test_decode_generate_steady_state(guard):
    """The batch-decode entry point: the second identical call runs the
    cached program (zero compiles) on the dp=2 x tp=2 CPU mesh."""
    from hivedscheduler_tpu.parallel import topology

    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(1))
    axes = topology.MeshAxes(dp=2, tp=2)
    mesh = topology.make_mesh(axes, topology.get_devices(axes.size))
    run, param_sh, prompt_sh = decode.make_sharded_generate(
        cfg, mesh, max_new_tokens=4)
    sharded_params = jax.device_put(params, param_sh)
    prompt = jax.device_put(
        jnp.asarray(np.tile([[3, 1, 4]], (2, 1)), jnp.int32), prompt_sh)
    out1 = run(sharded_params, prompt)
    assert compileguard.counts().get("decode.generate") == 1
    with compileguard.budget(0):
        out2 = run(sharded_params, prompt)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
