"""MoE models through the continuous-batching engine.

The engine's ragged decode path routes every token with no-drop inference
capacity (S*k slots per expert — worst-case skew fits), so MoE serving
must be routing-exact: every stream equals single-request MoE decode, and
the engine features (slot churn, prefix cache, chunked prefill,
speculation with a dense draft) compose unchanged — they operate on KV
only, below the MLP/MoE split."""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import decode, serving, transformer as tm  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=96, d_model=48, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=96, max_seq_len=128, dtype=jnp.float32,
                n_experts=4, moe_top_k=2)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(3))
    return cfg, params


def vanilla(params, cfg, prompt, n):
    out = decode.generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, n,
        max_len=len(prompt) + n,
    )
    return [int(t) for t in np.asarray(out)[0]]


class TestMoEServing:
    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_interleaved_streams_match_moe_decode(self, setup):
        cfg, params = setup
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64)
        prompts = [[5, 9, 2], [17, 3, 88, 41], [1], [60, 22]]
        budgets = [6, 4, 7, 5]
        reqs = [eng.submit(p, n) for p, n in zip(prompts, budgets)]
        eng.run_until_drained()
        for req, p, n in zip(reqs, prompts, budgets):
            assert req.done
            assert req.tokens_out == vanilla(params, cfg, p, n), req.rid

    def test_moe_chunked_prefill_exact(self, setup):
        cfg, params = setup
        long = list(range(20, 60))
        prompts = [long, [7, 8], long + [5]]
        plain = serving.ServingEngine(params, cfg, max_batch=2, max_len=96)
        refs = [plain.submit(p, 5) for p in prompts]
        plain.run_until_drained()
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=96,
                                    prefill_chunk=8)
        reqs = [eng.submit(p, 5) for p in prompts]
        eng.run_until_drained()
        assert [r.tokens_out for r in reqs] == [r.tokens_out for r in refs]
        assert eng.prefill_chunks_done > 0

    @pytest.mark.slow  # tier-1 wall-time budget (ISSUE 15): composition
    # variant; tier-1 cousins: test_moe_chunked_prefill_exact +
    # test_moe_mesh_sharded_engine_exact here, and the dense prefix
    # exactness suite (tests/test_serving_prefix.py)
    def test_moe_prefix_cache_exact(self, setup):
        cfg, params = setup
        system = list(range(30, 62))
        prompts = [system + [1], system + [2, 3], system + [1, 4]]
        plain = serving.ServingEngine(params, cfg, max_batch=2, max_len=96)
        refs = [plain.submit(p, 4) for p in prompts]
        plain.run_until_drained()
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=96,
                                    prefix_cache_size=16)
        reqs = [eng.submit(p, 4) for p in prompts]
        eng.run_until_drained()
        assert [r.tokens_out for r in reqs] == [r.tokens_out for r in refs]
        assert eng.prefix_hits >= 1

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_moe_target_dense_draft_speculation_exact(self, setup):
        """Speculative serving with an MoE target and a small dense draft:
        greedy streams still equal vanilla MoE decode."""
        cfg, params = setup
        dcfg = cfg_of(n_experts=0, d_model=24, n_heads=2, n_kv_heads=1,
                      d_ff=48, n_layers=1)
        dparams = tm.init_params(dcfg, jax.random.PRNGKey(9))
        eng = serving.SpeculativeServingEngine(
            params, cfg, dparams, dcfg, gamma=2, max_batch=2, max_len=64,
        )
        prompts = [[5, 9, 2], [17, 3], [1, 2, 3, 4]]
        reqs = [eng.submit(p, 5) for p in prompts]
        eng.run_until_drained()
        for req, p in zip(reqs, prompts):
            assert req.tokens_out == vanilla(params, cfg, p, 5), req.rid
        assert eng.drafted > 0

    def test_moe_mesh_sharded_engine_exact(self, setup):
        """MoE serving over a dp x tp mesh (ep=1): expert weights shard
        their ff axis over tp; streams equal unsharded serving."""
        from hivedscheduler_tpu.parallel import topology

        cfg, params = setup
        mesh = topology.make_mesh(
            topology.MeshAxes(dp=2, tp=2), topology.get_devices(4)
        )
        eng = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                    mesh=mesh)
        a = eng.submit([5, 9, 2], 5)
        b = eng.submit([17, 3, 88, 41], 4)
        eng.run_until_drained()
        assert a.tokens_out == vanilla(params, cfg, [5, 9, 2], 5)
        assert b.tokens_out == vanilla(params, cfg, [17, 3, 88, 41], 4)
