"""Workload checkpoint/resume: save a sharded train state, restore it into a
fresh incarnation (different mesh layout), and verify training continues
bit-identically."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import transformer as tm  # noqa: E402
from hivedscheduler_tpu.parallel import checkpoint, topology  # noqa: E402
from hivedscheduler_tpu.parallel.train import make_sharded_train_step  # noqa: E402


def test_save_restore_roundtrip(tmp_path):
    cfg = tm.TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32,
    )
    mesh = topology.make_mesh(topology.MeshAxes(dp=2, tp=2), topology.get_devices(4))
    step_fn, init_fn, tok_sh = make_sharded_train_step(cfg, mesh)
    params, opt_state = init_fn(jax.random.PRNGKey(0))
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64), tok_sh
    )
    params, opt_state, _ = step_fn(params, opt_state, tokens)
    checkpoint.save(str(tmp_path), 1, params, opt_state)
    assert checkpoint.latest_step(str(tmp_path)) == 1

    # continue training the original for one more step (reference trajectory)
    ref_params, _, ref_loss = step_fn(params, opt_state, tokens)

    # "rescheduled onto another slice": fresh incarnation, different mesh
    # layout (tp -> dp), restore and take the same step
    mesh2 = topology.make_mesh(topology.MeshAxes(dp=4), topology.get_devices(4))
    step2_fn, init2_fn, tok_sh2 = make_sharded_train_step(cfg, mesh2)
    params2, opt2 = init2_fn(jax.random.PRNGKey(7))  # different init: overwritten
    step_no, params2, opt2 = checkpoint.restore(str(tmp_path), params2, opt2)
    assert step_no == 1
    tokens2 = jax.device_put(np.asarray(tokens), tok_sh2)
    params2, _, loss2 = step2_fn(params2, opt2, tokens2)
    assert np.allclose(float(loss2), float(ref_loss), atol=1e-5)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(params2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_restore_missing_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        checkpoint.restore(str(tmp_path / "nope"), {}, {})


def test_restore_params_only_from_full_checkpoint(tmp_path):
    """restore_params loads a full training checkpoint's params without
    needing (or matching) its optimizer tree — the inference / --init-from
    warm-start path, incl. LoRA runs whose adapter-only optimizer state
    never matches the pretraining checkpoint's."""
    cfg = tm.TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_seq_len=16, dtype=jnp.float32,
    )
    mesh = topology.make_mesh(topology.MeshAxes(dp=2), topology.get_devices(2))
    _, init_fn, _ = make_sharded_train_step(cfg, mesh)
    params, opt_state = init_fn(jax.random.PRNGKey(0))
    checkpoint.save(str(tmp_path), 7, params, opt_state)

    template = jax.tree.map(jnp.zeros_like, params)
    step, restored = checkpoint.restore_params(str(tmp_path), template)
    assert step == 7
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored, params,
    )
    with pytest.raises(FileNotFoundError):
        checkpoint.restore_params(str(tmp_path / "nope"), template)


def _tiny_state(scale=1.0):
    params = {"w": jnp.full((4, 4), scale, jnp.float32)}
    opt = {"m": jnp.zeros((4, 4), jnp.float32)}
    return params, opt


def test_save_is_atomic_crash_before_marker_invisible(tmp_path):
    """A crash between orbax's write and the commit marker leaves the step
    UNCOMMITTED: latest_step/restore fall back to the previous complete
    checkpoint (simulated by deleting the marker, exactly the window a
    mid-save kill leaves behind)."""
    import glob
    import os

    d = str(tmp_path)
    p1, opt = _tiny_state(1.0)
    p2, _ = _tiny_state(2.0)
    checkpoint.save(d, 1, p1, opt)
    checkpoint.save(d, 2, p2, opt)
    assert checkpoint.latest_step(d) == 2
    assert os.path.exists(os.path.join(d, "2", "hived_complete.json"))

    os.unlink(os.path.join(d, "2", "hived_complete.json"))  # the crash window
    assert checkpoint.latest_step(d) == 1
    template = {"w": jnp.zeros((4, 4), jnp.float32)}
    step, params = checkpoint.restore_params(d, template)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(params["w"]),
                                  np.full((4, 4), 1.0, np.float32))
    # full restore takes the same ladder
    step, params, opt2 = checkpoint.restore(
        d, template, {"m": jnp.zeros((4, 4), jnp.float32)})
    assert step == 1
    np.testing.assert_array_equal(np.asarray(params["w"]),
                                  np.full((4, 4), 1.0, np.float32))


def test_restore_falls_back_past_torn_committed_step(tmp_path):
    """Torn storage PAST the commit marker (truncated payload files): the
    restore ladder must log, skip the unreadable step and load the previous
    complete checkpoint rather than crash the new incarnation."""
    import glob
    import os

    d = str(tmp_path)
    p1, opt = _tiny_state(1.0)
    p3, _ = _tiny_state(3.0)
    checkpoint.save(d, 1, p1, opt)
    checkpoint.save(d, 3, p3, opt)
    for f in glob.glob(os.path.join(d, "3", "params", "d", "*")):
        with open(f, "wb") as fh:
            fh.truncate(3)  # torn data file despite the marker
    template = {"w": jnp.zeros((4, 4), jnp.float32)}
    step, params = checkpoint.restore_params(d, template)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(params["w"]),
                                  np.full((4, 4), 1.0, np.float32))
    # an EXPLICITLY requested step must not silently fall back
    with pytest.raises(Exception):
        checkpoint.restore_params(d, template, step=3)


def test_extra_metadata_commits_with_the_marker(tmp_path):
    """Sidecar state of record (loader RNG position) rides INSIDE the
    commit marker: read_metadata returns it for a committed step, {} for
    legacy markers, missing steps and missing directories — and deleting
    the marker (the mid-save crash window) atomically loses arrays AND
    metadata together."""
    import os

    d = str(tmp_path)
    params, opt = _tiny_state(1.0)
    extra = {"loader": {"seed": 3, "step": 5, "epoch": 0,
                        "bitgen": {"bit_generator": "PCG64"}}}
    checkpoint.save(d, 5, params, opt, extra=extra)
    assert checkpoint.read_metadata(d) == extra
    assert checkpoint.read_metadata(d, 5) == extra
    # a later save without extra: newest metadata is {} (legacy shape)
    checkpoint.save(d, 6, params, opt)
    assert checkpoint.read_metadata(d) == {}
    assert checkpoint.read_metadata(d, 5) == extra
    # absent step / absent dir are best-effort empty, never a raise
    assert checkpoint.read_metadata(d, 99) == {}
    assert checkpoint.read_metadata(str(tmp_path / "nope")) == {}
    # the crash window: no marker => no metadata, same as no arrays
    os.unlink(os.path.join(d, "6", "hived_complete.json"))
    assert checkpoint.latest_step(d) == 5
    assert checkpoint.read_metadata(d) == extra


def test_atomic_write_bytes_replaces_whole_file(tmp_path):
    target = tmp_path / "latest"
    checkpoint.atomic_write_bytes(str(target), b"one")
    assert target.read_bytes() == b"one"
    checkpoint.atomic_write_bytes(str(target), b"two-longer")
    assert target.read_bytes() == b"two-longer"
    # no temp droppings left behind
    assert [p.name for p in tmp_path.iterdir()] == ["latest"]
