"""Workload supervisor units (fast, in-process): preemption listener,
watchdog, divergence guard, fault hooks, rollback budget. The subprocess
fault-ladder soaks live in tests/test_workload_chaos.py (slow-marked)."""

import os
import signal
import threading
import time

import pytest

from hivedscheduler_tpu.parallel import supervisor as sup_lib


class TestPreemptionListener:
    def test_signal_sets_event_and_uninstall_restores_handlers(self):
        prev_term = signal.getsignal(signal.SIGTERM)
        prev_int = signal.getsignal(signal.SIGINT)
        listener = sup_lib.PreemptionListener().install()
        try:
            assert not listener.requested
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(200):  # handler runs between bytecodes
                if listener.requested:
                    break
                time.sleep(0.01)
            assert listener.requested
            assert listener.signum == signal.SIGTERM
        finally:
            listener.uninstall()
        assert signal.getsignal(signal.SIGTERM) is prev_term
        assert signal.getsignal(signal.SIGINT) is prev_int

    def test_trigger_is_programmatic_preemption(self):
        listener = sup_lib.PreemptionListener()
        assert not listener.requested
        listener.trigger()
        assert listener.requested and listener.event.is_set()

    def test_grace_timer_fires_after_trigger(self):
        fired = threading.Event()
        listener = sup_lib.PreemptionListener(
            grace_secs=0.05, on_grace_exceeded=fired.set)
        listener.trigger()
        assert fired.wait(5.0), "grace backstop never fired"
        listener.uninstall()

    def test_no_grace_timer_without_grace(self):
        fired = threading.Event()
        listener = sup_lib.PreemptionListener(
            grace_secs=0.0, on_grace_exceeded=fired.set)
        listener.trigger()
        assert not fired.wait(0.2)


class TestWatchdog:
    def test_fires_on_stall_and_writes_record(self, tmp_path):
        records = []
        wd = sup_lib.Watchdog(0.05, first_step_factor=1.0,
                              record_dir=str(tmp_path), poll_s=0.01,
                              on_stall=records.append)
        wd.start()
        wd.heartbeat(1)
        wd.heartbeat(2)  # two beats: steady-state deadline armed
        deadline = time.monotonic() + 5.0
        while not wd.fired and time.monotonic() < deadline:
            time.sleep(0.01)
        wd.stop()
        assert wd.fired and records
        assert records[0]["last_step"] == 2
        assert records[0]["kind"] == "watchdog_stall"
        import json

        rec = json.loads((tmp_path / sup_lib.STALL_RECORD).read_text())
        assert rec["last_step"] == 2 and rec["pid"] == os.getpid()

    def test_does_not_fire_while_heartbeating(self):
        wd = sup_lib.Watchdog(0.2, first_step_factor=1.0, poll_s=0.02,
                              on_stall=lambda r: None)
        wd.start()
        t0 = time.monotonic()
        step = 0
        while time.monotonic() - t0 < 0.8:
            wd.heartbeat(step)
            step += 1
            time.sleep(0.02)
        assert not wd.fired
        wd.stop()

    def test_first_step_gets_scaled_deadline(self):
        """Beat #1 lands BEFORE the compile-heavy first step, so the scaled
        deadline must hold until the SECOND heartbeat."""
        wd = sup_lib.Watchdog(0.05, first_step_factor=100.0, poll_s=0.01,
                              on_stall=lambda r: None)
        wd.start()
        wd.heartbeat(0)  # one beat only: still inside the "first step"
        time.sleep(0.3)  # 6x the steady deadline
        assert not wd.fired, "watchdog fired during the simulated compile"
        wd.stop()

    def test_rejects_nonpositive_deadline(self):
        with pytest.raises(ValueError, match="must be > 0"):
            sup_lib.Watchdog(0.0)


class TestDivergenceGuard:
    def test_nonfinite_always_diverges(self):
        g = sup_lib.DivergenceGuard()
        assert g.check(1, float("nan"))
        assert g.check(2, float("inf"))
        assert g.check(3, 4.2) is None

    def test_spike_detection_after_warmup(self):
        g = sup_lib.DivergenceGuard(spike_factor=3.0, warmup_steps=3)
        for s in range(3):
            assert g.check(s, 1.0) is None
        assert g.check(3, 100.0) is not None  # 100 > 3 x EMA(1.0)
        # reset forgets the history (post-rollback)
        g.reset()
        assert g.check(4, 100.0) is None  # warming up again

    def test_no_spike_detection_by_default(self):
        g = sup_lib.DivergenceGuard()
        for s in range(10):
            assert g.check(s, 1.0) is None
        assert g.check(10, 1e9) is None  # huge but finite: not divergence


class TestFaultInjection:
    def test_from_env_and_one_shot(self, monkeypatch):
        monkeypatch.setenv(sup_lib.ENV_FAULT_NAN_AT, "3")
        monkeypatch.setenv(sup_lib.ENV_FAULT_SERVE_PREEMPT_AT, "5")
        faults = sup_lib.FaultInjection.from_env()
        assert faults.hang_at is None
        assert not faults.take_nan(2)
        assert faults.take_nan(3)
        assert not faults.take_nan(3)  # one-shot: a rollback replay is safe
        assert faults.take_serve_preempt(5)
        assert not faults.take_serve_preempt(5)

    def test_unarmed_is_inert(self, monkeypatch):
        for name in (sup_lib.ENV_FAULT_HANG_AT, sup_lib.ENV_FAULT_NAN_AT,
                     sup_lib.ENV_FAULT_SERVE_PREEMPT_AT,
                     sup_lib.ENV_FAULT_STEP_DELAY):
            monkeypatch.delenv(name, raising=False)
        faults = sup_lib.FaultInjection.from_env()
        assert not faults.take_nan(1)
        faults.maybe_hang(1)  # returns immediately
        faults.pace()
        assert faults.step_delay_s == 0.0


class TestSupervisor:
    def test_context_manager_and_rollback_budget(self):
        with sup_lib.Supervisor(install_signals=False,
                                max_rollbacks=2) as sup:
            assert not sup.preempt_requested
            assert sup.check_loss(1, 2.5) is None
            assert sup.check_loss(2, float("nan")) is not None
            assert sup.note_rollback()
            assert sup.note_rollback()
            assert not sup.note_rollback()  # budget exhausted -> halt

    def test_watchdog_wired_through(self):
        stalls = []
        with sup_lib.Supervisor(install_signals=False, watchdog_secs=0.05,
                                first_step_factor=1.0,
                                on_stall=stalls.append) as sup:
            sup.heartbeat(0)
            sup.heartbeat(1)
            deadline = time.monotonic() + 5.0
            while not stalls and time.monotonic() < deadline:
                time.sleep(0.01)
        assert stalls and stalls[0]["last_step"] == 1

    def test_preemption_event_reaches_prefetch(self):
        """The supervisor's preemption event is the prefetch stop event:
        a consumer blocked on a wedged producer must wake when preemption
        is requested (the grace period cannot be met otherwise)."""
        import numpy as np

        from hivedscheduler_tpu.parallel import data as data_lib

        release = threading.Event()

        def wedged():
            yield np.zeros((1,), np.int32)
            release.wait(30.0)  # simulated hung data source
            yield np.ones((1,), np.int32)

        # grace_secs=0: an armed grace timer would force-exit THIS process
        # (the production behavior) — the exit path has its own tests
        sup = sup_lib.Supervisor(install_signals=False, grace_secs=0.0)
        it = data_lib.prefetch(wedged(), depth=2,
                               stop=sup.preemption.event)
        try:
            next(it)
            threading.Timer(0.1, sup.preemption.trigger).start()
            t0 = time.monotonic()
            with pytest.raises(StopIteration):
                next(it)
            assert time.monotonic() - t0 < 5.0
        finally:
            release.set()
            sup.preemption.uninstall()  # cancels any armed grace timer
