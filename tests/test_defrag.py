"""Defragmentation subsystem: planner/probe/backfill units and the bench
trace-replay guards (ISSUE 9).

The probe's transactional rollback is the foundation everything rests on —
it is checked here bit-exact (placements AND the incremental VC-safety
counters), and every chaos soak re-checks it structurally via
``invariants.check_all``. The kill-switch differential pins
``HIVED_DEFRAG=0`` to the exact pre-defrag trace-replay numbers captured
before this subsystem landed.
"""

import copy
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402
from hivedscheduler_tpu.algorithm.hived import HivedAlgorithm  # noqa: E402
from hivedscheduler_tpu.api import constants as C  # noqa: E402
from hivedscheduler_tpu.api.config import Config, new_config  # noqa: E402
from hivedscheduler_tpu.api.types import (  # noqa: E402
    CellTypeSpec,
    MeshLevelSpec,
    MeshSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    VirtualCellSpec,
    VirtualClusterSpec,
)
from hivedscheduler_tpu.chaos import invariants  # noqa: E402
from hivedscheduler_tpu.common.utils import to_json  # noqa: E402
from hivedscheduler_tpu.defrag import (  # noqa: E402
    BackfillPolicy,
    GangSpec,
    MigrationPlanner,
    PlanRejected,
    RunningGroup,
    WhatIfProbe,
)
from hivedscheduler_tpu.defrag.planner import vc_quota_chips  # noqa: E402
from hivedscheduler_tpu.k8s.types import Container, Node, Pod  # noqa: E402
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE  # noqa: E402
from hivedscheduler_tpu.runtime.utils import new_binding_pod  # noqa: E402


def mini_config(cells: int = 2) -> Config:
    """One 2x2x2 v5p pod (two 4-chip host cells), one VC owning ``cells``
    of them — the smallest cluster where fragmentation is expressible."""
    mesh = MeshSpec(
        topology=(2, 2, 2), chip_type="v5p-chip", host_shape=(2, 2, 1),
        levels=[MeshLevelSpec(name="m-2x2x1", shape=(2, 2, 1)),
                MeshLevelSpec(name="m-2x2x2", shape=(2, 2, 2))],
    )
    return new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={"pod8": CellTypeSpec(mesh=mesh)},
            physical_cells=[
                PhysicalCellSpec(cell_type="pod8", cell_address="p0")],
        ),
        virtual_clusters={
            "vc-x": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=cells,
                                cell_type="pod8.m-2x2x1")]),
        },
    ))


def make_pod(name, group, chips, vc="vc-x", prio=5, pods=1):
    spec = {
        "virtualCluster": vc, "priority": prio,
        "leafCellType": "v5p-chip", "leafCellNumber": chips,
        "affinityGroup": {
            "name": group,
            "members": [{"podNumber": pods, "leafCellNumber": chips}],
        },
    }
    return Pod(
        name=name, uid=name,
        annotations={C.ANNOTATION_POD_SCHEDULING_SPEC: to_json(spec)},
        containers=[Container(
            resource_limits={C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})],
    )


def fresh_algo():
    algo = HivedAlgorithm(mini_config())
    nodes = sorted({n for ccl in algo.full_cell_list.values()
                    for c in ccl[max(ccl)] for n in c.nodes})
    for n in nodes:
        algo.add_node(Node(name=n))
    return algo, nodes


def place(algo, nodes, pod):
    r = algo.schedule(pod, nodes, FILTERING_PHASE)
    assert r.pod_bind_info is not None, f"{pod.name} should place"
    bp = new_binding_pod(pod, r.pod_bind_info)
    algo.add_allocated_pod(bp)
    return bp


def fragmented_state():
    """g1+g2 fill cell A, g3 takes half of cell B; g2 dies. Both cells are
    now half-used: a 4-chip gang has 4 free quota chips but no free cell —
    the canonical migration scenario."""
    algo, nodes = fresh_algo()
    g1 = place(algo, nodes, make_pod("g1-0", "g1", 2))
    g2 = place(algo, nodes, make_pod("g2-0", "g2", 2))
    g3 = place(algo, nodes, make_pod("g3-0", "g3", 2))
    algo.delete_allocated_pod(g2)
    return algo, nodes, {"g1": [g1], "g3": [g3]}


def running_groups(groups):
    return [RunningGroup(name=n, spec=GangSpec.from_pod(pods[0]),
                         bound_pods=pods) for n, pods in groups.items()]


# ---------------------------------------------------------------------------
# backfill policy (pure decision function)
# ---------------------------------------------------------------------------

class TestBackfillPolicy:
    def test_opportunistic_always_rides(self):
        d = BackfillPolicy().admits(priority=-1, now=100.0)
        assert d.admit and d.reason == "preemptible"

    def test_guaranteed_fits_window(self):
        d = BackfillPolicy(slack=1.0).admits(
            priority=5, now=0.0, duration=10.0, reservation_eta=10.0)
        assert d.admit and d.reason == "fits-window"

    def test_guaranteed_would_delay_waiter(self):
        d = BackfillPolicy(slack=1.0).admits(
            priority=5, now=0.0, duration=10.1, reservation_eta=10.0)
        assert not d.admit and d.reason == "would-delay-waiter"

    def test_guaranteed_unknown_duration_rejected(self):
        d = BackfillPolicy().admits(priority=5, now=0.0)
        assert not d.admit and d.reason == "unknown-duration"

    def test_slack_pads_the_estimate(self):
        # 8 * 1.25 = 10 > 9: optimistic estimates get margin
        d = BackfillPolicy(slack=1.25).admits(
            priority=5, now=0.0, duration=8.0, reservation_eta=9.0)
        assert not d.admit

    def test_slack_below_one_rejected(self):
        with pytest.raises(ValueError, match="slack must be >= 1.0"):
            BackfillPolicy(slack=0.5)


# ---------------------------------------------------------------------------
# what-if probe: transactional, bit-exact rollback
# ---------------------------------------------------------------------------

def _books(algo):
    return {
        "total_left": copy.deepcopy(algo.total_left_cell_num),
        "all_vc_free": copy.deepcopy(algo.all_vc_free_cell_num),
        "vc_free": copy.deepcopy(algo.vc_free_cell_num),
        "free_lists": {
            str(ch): {lv: sorted(c.address for c in fl[lv])
                      for lv in sorted(fl)}
            for ch, fl in algo.free_cell_list.items()
        },
        "placements": invariants.placement_snapshot(algo),
    }


class TestWhatIfProbe:
    def test_feasible_probe_rolls_back_bit_exact(self):
        algo, nodes, groups = fragmented_state()
        before = _books(algo)
        probe = WhatIfProbe(algo, nodes)
        waiter = GangSpec(name="w", vc="vc-x", priority=5,
                          leaf_cell_type="v5p-chip", members=((1, 4),))
        g1 = running_groups(groups)[0]
        res = probe.run_probe(waiter, [(g1.name, g1.spec, g1.bound_pods)])
        assert res.feasible
        assert "w" in res.placements and g1.name in res.placements
        assert _books(algo) == before
        invariants.check_all(algo, "post-probe")

    def test_infeasible_probe_rolls_back_too(self):
        algo, nodes, groups = fragmented_state()
        before = _books(algo)
        probe = WhatIfProbe(algo, nodes)
        # 8 chips cannot exist in a 2-cell VC with 4 chips used: the waiter
        # itself is unplaceable whatever moves
        waiter = GangSpec(name="w", vc="vc-x", priority=5,
                          leaf_cell_type="v5p-chip", members=((2, 4),))
        gs = running_groups(groups)
        res = probe.run_probe(
            waiter, [(g.name, g.spec, g.bound_pods) for g in gs])
        assert not res.feasible and "unplaceable" in res.reason
        assert _books(algo) == before
        invariants.check_all(algo, "post-failed-probe")

    def test_swap_probe_promotion_question(self):
        algo, nodes = fresh_algo()
        opp = place(algo, nodes, make_pod("o-0", "o", 4, prio=-1))
        before = _books(algo)
        probe = WhatIfProbe(algo, nodes)
        group = RunningGroup(name="o", spec=GangSpec.from_pod(opp),
                            bound_pods=[opp])
        import dataclasses
        promoted = dataclasses.replace(group.spec, priority=5)
        res = probe.run_swap_probe([opp], promoted)
        assert res.feasible and "o" in res.placements
        assert _books(algo) == before
        invariants.check_all(algo, "post-swap-probe")


# ---------------------------------------------------------------------------
# migration planner
# ---------------------------------------------------------------------------

class TestMigrationPlanner:
    WAITER = GangSpec(name="w", vc="vc-x", priority=5,
                      leaf_cell_type="v5p-chip", members=((1, 4),))

    def test_single_move_plan_found(self):
        algo, nodes, groups = fragmented_state()
        plan = MigrationPlanner().plan_migration(
            WhatIfProbe(algo, nodes), self.WAITER, running_groups(groups),
            free_chips=4)
        assert hasattr(plan, "moves"), plan
        assert len(plan.moves) == 1 and plan.moved_chips == 2
        assert plan.waiter_nodes and plan.moves[0].target_nodes
        # the waiter's slice and the move target never overlap
        assert not set(plan.waiter_nodes) & set(plan.moves[0].target_nodes)
        invariants.check_all(algo, "post-plan")

    def test_capacity_short_circuits_without_probes(self):
        algo, nodes, groups = fragmented_state()
        plan = MigrationPlanner().plan_migration(
            WhatIfProbe(algo, nodes), self.WAITER, running_groups(groups),
            free_chips=2)
        assert isinstance(plan, PlanRejected)
        assert plan.reason == "capacity" and plan.probes_spent == 0

    def test_no_candidates_when_all_higher_priority(self):
        algo, nodes, groups = fragmented_state()
        waiter = GangSpec(name="w", vc="vc-x", priority=1,
                          leaf_cell_type="v5p-chip", members=((1, 4),))
        plan = MigrationPlanner().plan_migration(
            WhatIfProbe(algo, nodes), waiter, running_groups(groups))
        assert isinstance(plan, PlanRejected)
        assert plan.reason == "no-candidates"

    def test_guaranteed_waiter_only_considers_same_vc_guaranteed(self):
        planner = MigrationPlanner()
        waiter = self.WAITER
        same_vc = RunningGroup(
            name="a", bound_pods=[],
            spec=GangSpec(name="a", vc="vc-x", priority=5,
                          leaf_cell_type="v5p-chip", members=((1, 2),)))
        other_vc = RunningGroup(
            name="b", bound_pods=[],
            spec=GangSpec(name="b", vc="vc-y", priority=5,
                          leaf_cell_type="v5p-chip", members=((1, 2),)))
        opportunistic = RunningGroup(
            name="c", bound_pods=[],
            spec=GangSpec(name="c", vc="vc-x", priority=-1,
                          leaf_cell_type="v5p-chip", members=((1, 2),)))
        assert planner._movable_for(waiter, same_vc)
        assert not planner._movable_for(waiter, other_vc)
        assert not planner._movable_for(waiter, opportunistic)
        opp_waiter = GangSpec(name="w", vc="vc-x", priority=-1,
                              leaf_cell_type="v5p-chip", members=((1, 4),))
        assert planner._movable_for(opp_waiter, opportunistic)
        assert not planner._movable_for(opp_waiter, same_vc)

    def test_probe_budget_bounds_the_search(self):
        algo, nodes, groups = fragmented_state()
        plan = MigrationPlanner(max_probes=0).plan_migration(
            WhatIfProbe(algo, nodes), self.WAITER, running_groups(groups))
        assert isinstance(plan, PlanRejected)
        assert "probe budget" in plan.detail

    def test_not_worth_it_economics(self):
        algo, nodes, groups = fragmented_state()
        # moving 2 chips at downtime 100 to save a 4-chip waiter 1 time
        # unit scores 4/200 << 1
        plan = MigrationPlanner(move_downtime=100.0).plan_migration(
            WhatIfProbe(algo, nodes), self.WAITER, running_groups(groups),
            waiter_wait_estimate=1.0)
        assert isinstance(plan, PlanRejected)
        assert plan.reason == "not-worth-it"

    def test_promotion_plan(self):
        algo, nodes = fresh_algo()
        opp = place(algo, nodes, make_pod("o-0", "o", 4, prio=-1))
        group = RunningGroup(name="o", spec=GangSpec.from_pod(opp),
                            bound_pods=[opp])
        plan = MigrationPlanner().plan_promotion(
            WhatIfProbe(algo, nodes), group, to_priority=5)
        assert hasattr(plan, "moves")
        assert plan.waiter.priority == 5 and plan.waiter.name == "o"
        invariants.check_all(algo, "post-promotion-plan")

    def test_vc_quota_chips_static(self):
        algo, _ = fresh_algo()
        assert vc_quota_chips(algo, "vc-x") == 8
        assert vc_quota_chips(algo, "no-such-vc") == 0
        cluster = bench.Cluster()
        assert vc_quota_chips(cluster.algo, "vc-a") == 512
        assert vc_quota_chips(cluster.algo, "vc-b") == 256
        assert vc_quota_chips(cluster.algo, "vc-c") == 256


# ---------------------------------------------------------------------------
# bench trace replay: kill-switch differential + the packing-gap win
# ---------------------------------------------------------------------------

# Deterministic fields of bench.run_trace(n_jobs=80, seed=11), captured on
# the pre-defrag tree (PR 8 head, f47ecad) — the HIVED_DEFRAG=0 contract:
# the kill switch must reproduce these exactly, forever.
PRE_DEFRAG_GOLDEN_80 = {
    "jobs": 80, "scheduled": 80, "preemption_events": 5,
    "utilization_pct": 37.1, "offered_pct": 37.8, "contiguous_pct": 97.5,
    "bbox_inflation": 1.025, "wait_chip_time_pct": 6.0,
    "wait_capacity_share": 0.0, "wait_packing_share": 1.0,
    "preempt_wasted_pct": 1.1, "wait_p50_t": 0.0,
}


class TestTraceDefrag:
    def test_kill_switch_reproduces_pre_defrag_trace(self, monkeypatch):
        monkeypatch.setenv("HIVED_DEFRAG", "0")
        r = bench.run_trace(n_jobs=80, seed=11)
        for k, v in PRE_DEFRAG_GOLDEN_80.items():
            assert r[k] == v, f"{k}: {r[k]} != golden {v}"
        # and none of the defrag-mode fields leak into the artifact
        assert "migrations" not in r and "backfills" not in r

    def test_defrag_closes_the_packing_gap(self, monkeypatch):
        # n=200 is the smallest scale where the full acceptance shape
        # shows in seconds: packing share collapses, utilization jumps,
        # contiguity holds, and the machinery demonstrably ran
        on = bench.run_trace(n_jobs=200, seed=11)
        monkeypatch.setenv("HIVED_DEFRAG", "0")
        off = bench.run_trace(n_jobs=200, seed=11)
        assert on["wait_packing_share"] < 0.5 < off["wait_packing_share"]
        assert on["utilization_pct"] >= off["utilization_pct"]
        assert on["contiguous_pct"] >= off["contiguous_pct"]
        assert on["backfills"] + on["migrations"] + on["promotions"] > 0
        assert on["migration_overhead_pct"] >= 0.0

    def test_defrag_trace_is_deterministic(self):
        a = bench.run_trace(n_jobs=60, seed=7)
        b = bench.run_trace(n_jobs=60, seed=7)
        wallclock = ("sched_p50_ms", "sched_p99_ms")
        assert ({k: v for k, v in a.items() if k not in wallclock}
                == {k: v for k, v in b.items() if k not in wallclock})

    @pytest.mark.slow
    def test_acceptance_scale_trace(self):
        """The ISSUE 9 acceptance numbers at full driver scale (n=300):
        utilization >= the naive baseline's 56.8, packing share < 0.5,
        contiguity >= the pre-defrag 89.7."""
        r = bench.run_trace(n_jobs=300, seed=11)
        assert r["utilization_pct"] >= 56.8
        assert r["wait_packing_share"] < 0.5
        assert r["contiguous_pct"] >= 89.7
        assert r["preempt_wasted_pct"] <= 4.5  # work-preserving preemption
