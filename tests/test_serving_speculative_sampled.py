"""Sampled speculative continuous batching (temperature > 0).

Properties under test:

1. **Perfect-draft bit-exactness**: with draft == target, every proposal
   is drawn with the same counter-based key the plain sampled engine
   would use at that emitted position, acceptance is certain (p == q),
   and the bonus token uses the plain key over the same filtered logits
   — so the speculative engine's sampled stream equals the plain
   engine's bit for bit.
2. **Interleaving independence**: per-row keyed draws (seed x rid x
   position, tagged per purpose) make sampled speculative streams
   independent of arrival order and batch composition.
3. **Validity under a weak draft**: residual resampling emits in-vocab
   tokens, requests complete, acceptance stays in [0, 1].
"""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import serving, transformer as tm  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=128, max_seq_len=128, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = cfg_of()
    params = tm.init_params(cfg, jax.random.PRNGKey(0))
    dft_cfg = cfg_of(d_model=32, n_heads=2, n_kv_heads=1, d_ff=64,
                     n_layers=1)
    dft_params = tm.init_params(dft_cfg, jax.random.PRNGKey(7))
    return cfg, params, dft_cfg, dft_params


SAMPLING = dict(temperature=0.8, top_k=20, top_p=0.9, seed=5)


class TestSampledSpeculativeServing:
    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_perfect_draft_matches_plain_sampled_engine(self, setup):
        cfg, params, _, _ = setup
        prompts = [[5, 9, 2], [17, 3, 88], [1, 4]]
        plain = serving.ServingEngine(params, cfg, max_batch=2, max_len=64,
                                      **SAMPLING)
        refs = [plain.submit(p, 6) for p in prompts]
        plain.run_until_drained()
        eng = serving.SpeculativeServingEngine(
            params, cfg, params, cfg, gamma=3, max_batch=2, max_len=64,
            **SAMPLING,
        )
        reqs = [eng.submit(p, 6) for p in prompts]
        eng.run_until_drained()
        assert [r.tokens_out for r in reqs] == [r.tokens_out for r in refs]
        assert eng.acceptance == 1.0

    def test_weak_draft_completes_with_valid_tokens(self, setup):
        cfg, params, dft_cfg, dft_params = setup
        eng = serving.SpeculativeServingEngine(
            params, cfg, dft_params, dft_cfg, gamma=3, max_batch=2,
            max_len=64, **SAMPLING,
        )
        prompts = [[5, 9, 2], [17, 3, 88, 41], [1], [100, 22, 63]]
        budgets = [6, 4, 8, 5]
        reqs = [eng.submit(p, n) for p, n in zip(prompts, budgets)]
        eng.run_until_drained()
        for req, n in zip(reqs, budgets):
            assert req.done and len(req.tokens_out) == n
            assert all(0 <= t < cfg.vocab_size for t in req.tokens_out)
        assert 0.0 <= eng.acceptance <= 1.0

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_sampled_streams_reproducible_under_interleaving(self, setup):
        cfg, params, dft_cfg, dft_params = setup

        def make():
            return serving.SpeculativeServingEngine(
                params, cfg, dft_params, dft_cfg, gamma=2, max_batch=2,
                max_len=64, **SAMPLING,
            )

        # engine A: both requests arrive together
        a = make()
        a0 = a.submit([4, 8], 5)
        a1 = a.submit([9, 1, 7], 6)
        a.run_until_drained()
        # engine B: same rids, second request arrives mid-decode
        b = make()
        b0 = b.submit([4, 8], 5)
        b.step()
        b1 = b.submit([9, 1, 7], 6)
        b.run_until_drained()
        assert a0.tokens_out == b0.tokens_out
        assert a1.tokens_out == b1.tokens_out

    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_sampled_composes_with_chunked_prefill(self, setup):
        """Chunking stays a pure scheduling change for the SAMPLED
        speculative engine too: same streams with and without it."""
        cfg, params, dft_cfg, dft_params = setup
        long = list(range(20, 50))
        prompts = [long, [7, 8], long + [5]]

        def run(**kw):
            eng = serving.SpeculativeServingEngine(
                params, cfg, dft_params, dft_cfg, gamma=2, max_batch=2,
                max_len=96, **SAMPLING, **kw,
            )
            reqs = [eng.submit(p, 5) for p in prompts]
            eng.run_until_drained()
            return eng, [r.tokens_out for r in reqs]

        _, plain = run()
        eng, chunked = run(prefill_chunk=8)
        assert chunked == plain
        assert eng.prefill_chunks_done > 0
