"""Randomized invariant-checking stress harness for the scheduler core.

Golden tests pin known decision sequences; this harness explores the state
space the goldens can't: hundreds of random schedule / allocate / delete /
preempt / bad-node / cancel operations, with the algorithm's structural
invariants re-derived FROM SCRATCH and checked after every operation:

- **VC safety** (the paper's core guarantee, hived_algorithm.go:1242-1292):
  totalLeftCellNum[chain][level] >= allVCFreeCellNum[chain][level] always.
- **Used-count books**: every cell's used_leaf_cell_num_at_priorities dict
  equals a recount of its allocated leaf descendants — this directly guards
  the batched bookkeeping walks (UsedCountBatch) against drift.
- **Priority max-invariant**: parent priority == max(children priorities)
  on both trees (reference setCellPriority, cell_allocation.go:425-441).
- **Free-list hygiene**: free cells carry FREE priority, no using group,
  and a consistent parent split flag.
- **Full-delete restoration**: after deleting every gang and healing every
  node, the entire reachable state (free lists, counters, priorities,
  states, bindings) equals a freshly built algorithm's — the reference's
  testDeletePods invariant (hived_algorithm_test.go:734) at fuzz scale.
"""

import logging
import random

import pytest

from hivedscheduler_tpu.algorithm.constants import (
    CELL_FREE,
    FREE_PRIORITY,
    LOWEST_LEVEL,
)
from hivedscheduler_tpu.algorithm.hived import HivedAlgorithm
from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.chaos import invariants as chaos_invariants
from hivedscheduler_tpu.api.config import Config, new_config
from hivedscheduler_tpu.api.types import (
    CellTypeSpec,
    MeshLevelSpec,
    MeshSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    VirtualCellSpec,
    VirtualClusterSpec,
)
from hivedscheduler_tpu.k8s.types import Node
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE, PREEMPTING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

from helpers import make_pod


@pytest.fixture(autouse=True)
def _mute_algorithm_logs():
    """The fuzz drives thousands of scheduler ops; scope the log muting to
    this module so caplog-style tests elsewhere keep seeing records."""
    logging.disable(logging.CRITICAL)
    yield
    logging.disable(logging.NOTSET)


def build_config() -> Config:
    """A v5p-64 mesh chain (4x4x4, 2x2x1 hosts) + a second, smaller v5p-32
    chain of the SAME chip type (so oversize gangs exercise multi-chain
    relaxation under fuzz) + a generic 16-chip chain, three VCs with mixed
    quotas."""
    mesh = MeshSpec(
        topology=(4, 4, 4), chip_type="v5p-chip", host_shape=(2, 2, 1),
        levels=[
            MeshLevelSpec(name="v5p-2x2x1", shape=(2, 2, 1)),
            MeshLevelSpec(name="v5p-2x2x2", shape=(2, 2, 2)),
            MeshLevelSpec(name="v5p-4x2x2", shape=(4, 2, 2)),
            MeshLevelSpec(name="v5p-4x4x2", shape=(4, 4, 2)),
            MeshLevelSpec(name="v5p-4x4x4", shape=(4, 4, 4)),
        ],
    )
    mesh_b = MeshSpec(
        topology=(4, 4, 2), chip_type="v5p-chip", host_shape=(2, 2, 1),
        levels=[
            MeshLevelSpec(name="v5p32-2x2x1", shape=(2, 2, 1)),
            MeshLevelSpec(name="v5p32-2x2x2", shape=(2, 2, 2)),
            MeshLevelSpec(name="v5p32-4x2x2", shape=(4, 2, 2)),
            MeshLevelSpec(name="v5p32-4x4x2", shape=(4, 4, 2)),
        ],
    )
    generic = CellTypeSpec(
        child_cell_type="v4-node", child_cell_number=4, is_node_level=False,
    )
    v4_node = CellTypeSpec(
        child_cell_type="v4-chip", child_cell_number=4, is_node_level=True,
    )
    return new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={
                "v5p-64": CellTypeSpec(mesh=mesh),
                "v5p-32": CellTypeSpec(mesh=mesh_b),
                "v4-pool": generic,
                "v4-node": v4_node,
            },
            physical_cells=[
                PhysicalCellSpec(cell_type="v5p-64", cell_address="pod0"),
                PhysicalCellSpec(cell_type="v5p-32", cell_address="pod1"),
                PhysicalCellSpec(cell_type="v4-pool", cell_address="pool0"),
            ],
        ),
        virtual_clusters={
            "vc-a": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=1, cell_type="v5p-64.v5p-4x4x2"),
                VirtualCellSpec(cell_number=1, cell_type="v5p-32.v5p32-4x2x2"),
                VirtualCellSpec(cell_number=2, cell_type="v4-pool.v4-node"),
            ]),
            "vc-b": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=2, cell_type="v5p-64.v5p-2x2x2"),
            ]),
            "vc-c": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=2, cell_type="v5p-64.v5p-2x2x1"),
                VirtualCellSpec(cell_number=1, cell_type="v4-pool.v4-node"),
            ]),
        },
    ))


def all_cells(ccl):
    for level in sorted(ccl):
        for c in ccl[level]:
            yield c


def leaf_descendants(c):
    if not c.children:
        yield c
        return
    for ch in c.children:
        yield from leaf_descendants(ch)


class Harness:
    def __init__(self, seed: int):
        self.rng = random.Random(seed)
        self.algo = HivedAlgorithm(build_config())
        self.nodes = sorted({
            n for ccl in self.algo.full_cell_list.values()
            for c in ccl[max(ccl)] for n in c.nodes
        })
        for n in self.nodes:
            self.algo.add_node(Node(name=n))
        self.bad_nodes = set()
        self.groups = {}  # name -> list of bound pods
        self.gid = 0

    # ---------------- operations ----------------

    def op_schedule_gang(self):
        rng = self.rng
        vc = rng.choice(["vc-a", "vc-b", "vc-c"])
        prio = rng.choice([-1, -1, 0, 1, 5, 10])
        leaf_type = rng.choice(["v5p-chip", "v5p-chip", "v4-chip"])
        # (12, 4) = 48 chips exceeds vc-a's per-chain v5p quota (32 on
        # the big chain + 16 on the small one), so a GUARANTEED vc-a draw
        # can only be satisfied by a multi-chain-relaxed split; other draws
        # exercise the rejection/opportunistic paths
        pods, chips = rng.choice([(1, 1), (1, 2), (1, 4), (2, 4), (4, 4),
                                  (2, 8), (8, 4), (12, 4)])
        name = f"g{self.gid}"
        self.gid += 1
        spec = {
            "virtualCluster": vc, "priority": prio, "leafCellType": leaf_type,
            "leafCellNumber": chips,
            # fuzz BOTH relaxation partitions: the balanced water-fill's
            # cumulative-allowance pass (and its fewest-allowance rerun on
            # estimate shortfall) must uphold every invariant the greedy
            # partition does, under churn, bad nodes and recovery replay
            "multiChainRelaxPolicy": rng.choice(["fewest", "balanced"]),
            "affinityGroup": {
                "name": name,
                "members": [{"podNumber": pods, "leafCellNumber": chips}],
            },
        }
        bound = []
        for i in range(pods):
            pod = make_pod(f"{name}-{i}", spec)
            r = None
            for _attempt in range(64):
                phase = PREEMPTING_PHASE if _attempt else FILTERING_PHASE
                try:
                    r = self.algo.schedule(pod, self.nodes, phase)
                except api.WebServerError as e:
                    # a legitimate user-error rejection (e.g. a guaranteed
                    # request for a leaf type this VC has no quota of) —
                    # must be a 4xx and must leave no partial state behind
                    assert 400 <= e.code < 500, e
                    for bp in bound:
                        self.algo.delete_allocated_pod(bp)
                    return
                if r.pod_preempt_info is not None:
                    for victim in r.pod_preempt_info.victim_pods:
                        self._kill_owner(victim)
                    continue
                break
            if r.pod_bind_info is None:
                # gang unplaceable: roll back my pods AND cancel a possible
                # preempting group left behind (not all members placed)
                for bp in bound:
                    self.algo.delete_allocated_pod(bp)
                self.algo.delete_unallocated_pod(pod)
                return
            bp = new_binding_pod(pod, r.pod_bind_info)
            self.algo.add_allocated_pod(bp)
            bound.append(bp)
        self.groups[name] = bound

    def _kill_owner(self, victim):
        for name, pods in list(self.groups.items()):
            if any(bp.uid == victim.uid for bp in pods):
                self.op_delete_gang(name)
                return

    def op_delete_gang(self, name=None):
        if not self.groups:
            return
        name = name or self.rng.choice(list(self.groups))
        for bp in self.groups.pop(name):
            self.algo.delete_allocated_pod(bp)

    def op_flip_node(self):
        n = self.rng.choice(self.nodes)
        if n in self.bad_nodes:
            self.bad_nodes.discard(n)
            self.algo.add_node(Node(name=n))
        else:
            self.bad_nodes.add(n)
            self.algo.delete_node(Node(name=n))

    def heal_all(self):
        for n in sorted(self.bad_nodes):
            self.algo.add_node(Node(name=n))
        self.bad_nodes.clear()

    # ---------------- invariants ----------------

    def check_invariants(self, ctx="", allow_partial_placement=False):
        """One shared checker with the chaos harness and the pinned-seed
        replay tool: chaos.invariants re-derives VC safety, the used-count
        books, priority max-invariant, free-list hygiene, cell ownership
        (no leak / no double allocation) and structural gang atomicity from
        scratch (see that module for the per-invariant contracts).
        ``allow_partial_placement`` is for reconfiguration replays, whose
        tolerance ladder legitimately ignores vanished-chain placements."""
        chaos_invariants.check_all(
            self.algo, ctx, allow_partial_placement=allow_partial_placement
        )

    def snapshot(self):
        """Full reachable state of the physical + virtual trees."""
        a = self.algo
        snap = {}
        for chain, ccl in a.full_cell_list.items():
            for c in all_cells(ccl):
                snap[("P", chain, c.address)] = (
                    c.priority, c.state, c.healthy,
                    dict(c.used_leaf_cell_num_at_priorities),
                    c.virtual_cell.address if c.virtual_cell else None,
                    c.split,
                )
        for vcn, sched in a.vc_schedulers.items():
            for chain, ccl in sched.non_pinned_full_cell_list.items():
                for c in all_cells(ccl):
                    snap[("V", vcn, chain, c.address)] = (
                        c.priority, c.state, c.healthy,
                        dict(c.used_leaf_cell_num_at_priorities),
                        c.physical_cell.address if c.physical_cell else None,
                    )
        snap["free"] = {
            chain: {lvl: sorted(c.address for c in fl[lvl]) for lvl in fl}
            for chain, fl in a.free_cell_list.items()
        }
        snap["left"] = {c: dict(v) for c, v in a.total_left_cell_num.items()}
        snap["allvcfree"] = {c: dict(v) for c, v in a.all_vc_free_cell_num.items()}
        return snap


@pytest.mark.parametrize("seed", list(range(8)))
def test_fuzzed_operations_preserve_invariants(seed):
    h = Harness(seed)
    h.check_invariants("init")
    ops = [
        (h.op_schedule_gang, 5),
        (h.op_delete_gang, 3),
        (h.op_flip_node, 1),
    ]
    weighted = [f for f, w in ops for _ in range(w)]
    for i in range(400):
        h.rng.choice(weighted)()
        h.check_invariants(f"seed {seed} op {i}")
    assert h.gid > 100  # the fuzz actually scheduled things


@pytest.mark.parametrize("seed", [0, 7])
def test_full_delete_restores_pristine_state(seed):
    """After deleting every gang and healing every node, the whole reachable
    state must equal a fresh algorithm's (reference testDeletePods scaled)."""
    pristine = Harness(seed).snapshot()
    h = Harness(seed)
    for i in range(150):
        h.rng.choice(
            [h.op_schedule_gang, h.op_schedule_gang, h.op_schedule_gang,
             h.op_delete_gang, h.op_flip_node]
        )()
    h.heal_all()
    while h.groups:
        h.op_delete_gang()
    h.check_invariants("final")
    assert h.snapshot() == pristine


def _group_view(algo, with_lazy):
    out = {}
    for name, g in algo.affinity_groups.items():
        placement = {}
        for ln, podps in g.physical_leaf_cell_placement.items():
            placement[ln] = [
                sorted(c.address for c in podp if c is not None)
                for podp in podps
            ]
        view = (g.vc, g.priority, placement)
        if with_lazy:
            view += (
                g.virtual_leaf_cell_placement is None,
                g.lazy_preemption_status is None,
            )
        out[name] = view
    return out


def _replay(h, config=None):
    """The runtime's recovery barrier: fresh algorithm (optionally built
    from a reconfigured ``config``), healthy nodes informed, every bound
    pod replayed from its annotations. A node unknown to the new config
    (decommissioned chain) is a silent add_node no-op, matching the
    runtime's informer behavior."""
    fresh = HivedAlgorithm(config if config is not None else build_config())
    for n in h.nodes:
        if n not in h.bad_nodes:
            fresh.add_node(Node(name=n))
    for name in sorted(h.groups):
        for bp in h.groups[name]:
            fresh.add_allocated_pod(bp)
    h2 = Harness.__new__(Harness)  # reuse the invariant checker
    h2.algo = fresh
    return fresh, h2


@pytest.mark.parametrize("seed", [0, 3, 5])
def test_recovery_replay_preserves_state(seed):
    """Crash recovery at a fuzzed state with every node healthy at crash
    time: the replayed instance must carry the exact same groups —
    placement, VC, priority AND lazy-preemption status."""
    h = Harness(seed)
    for i in range(150):
        h.rng.choice(
            [h.op_schedule_gang, h.op_schedule_gang, h.op_schedule_gang,
             h.op_delete_gang, h.op_flip_node]
        )()
    h.heal_all()
    before = _group_view(h.algo, with_lazy=True)
    fresh, h2 = _replay(h)
    h2.check_invariants(f"seed {seed} after replay")
    assert _group_view(fresh, with_lazy=True) == before


@pytest.mark.parametrize("seed", [0, 3, 5])
def test_recovery_replay_under_bad_nodes(seed):
    """Crash recovery with arbitrary bad nodes at crash time. The reference
    panics or silently corrupts its books when init-time doomed-bad
    bindings collide with replayed placements (see the doomed-bad
    deviations in PARITY.md); we require a clean replay: invariants hold
    and every group keeps its placement, VC and priority. Lazy-preemption
    status is allowed to differ — the tolerance ladder deliberately
    lazy-preempts groups whose safety cannot be proven mid-replay."""
    h = Harness(seed)
    for i in range(150):
        h.rng.choice(
            [h.op_schedule_gang, h.op_schedule_gang, h.op_schedule_gang,
             h.op_delete_gang, h.op_flip_node]
        )()
    before = _group_view(h.algo, with_lazy=False)
    fresh, h2 = _replay(h)
    h2.check_invariants(f"seed {seed} after replay")
    assert _group_view(fresh, with_lazy=False) == before


def _mutated_config(kind: str) -> Config:
    """A config that differs from build_config() the way production
    reconfigurations do (the reference's testReconfiguration family,
    hived_algorithm_test.go:1042-1092, at fuzz scale)."""
    cfg = build_config()
    if kind == "drop_chain":
        # the v5p-32 chain is decommissioned: its physical cell and every
        # VC quota on it disappear
        cfg.physical_cluster.physical_cells = [
            pc for pc in cfg.physical_cluster.physical_cells
            if pc.cell_type != "v5p-32"
        ]
        del cfg.physical_cluster.cell_types["v5p-32"]
        for vc in cfg.virtual_clusters.values():
            vc.virtual_cells = [
                v for v in vc.virtual_cells
                if not v.cell_type.startswith("v5p-32.")
            ]
    elif kind == "shrink_vc":
        # vc-b loses half its quota
        for v in cfg.virtual_clusters["vc-b"].virtual_cells:
            if v.cell_type == "v5p-64.v5p-2x2x2":
                v.cell_number = 1
    elif kind == "swap_quota":
        # vc-c's v5p quota moves to vc-b (same physical capacity)
        cfg.virtual_clusters["vc-c"].virtual_cells = [
            v for v in cfg.virtual_clusters["vc-c"].virtual_cells
            if v.cell_type != "v5p-64.v5p-2x2x1"
        ]
        cfg.virtual_clusters["vc-b"].virtual_cells.append(
            VirtualCellSpec(cell_number=2, cell_type="v5p-64.v5p-2x2x1")
        )
    else:
        raise AssertionError(kind)
    # no second new_config(): address inference is not idempotent (it would
    # re-prefix the generic chain's already-inferred addresses), and the
    # mutations above only touch fields defaulting never derives from
    return cfg


@pytest.mark.parametrize("kind", ["drop_chain", "shrink_vc", "swap_quota"])
@pytest.mark.parametrize("seed", [0, 3])
def test_reconfig_replay_fuzz(seed, kind):
    """Work-preserving reconfiguration at fuzz scale: run random churn,
    then replay every bound pod into an algorithm built from a MUTATED
    config (dropped chain / shrunk VC / quota moved between VCs). The
    tolerance ladder must absorb every inconsistency — placements on
    vanished chains are ignored or cross-chain-recovered, unsafe or
    unmappable placements lazy-preempt — and the books must be consistent
    afterwards. No panic, no silent corruption."""
    h = Harness(seed)
    for i in range(150):
        h.rng.choice(
            [h.op_schedule_gang, h.op_schedule_gang, h.op_schedule_gang,
             h.op_delete_gang, h.op_flip_node]
        )()
    fresh, h2 = _replay(h, config=_mutated_config(kind))
    h2.check_invariants(f"seed {seed} kind {kind} after reconfig replay",
                        allow_partial_placement=True)
    # every replayed pod must be ABSORBED (registered in its group's slots)
    # — the ladder may demote or ignore placements, never lose pods
    absorbed = sum(
        sum(1 for pods in g.allocated_pods.values()
            for p in pods if p is not None)
        for g in fresh.affinity_groups.values()
    )
    assert absorbed == sum(len(pods) for pods in h.groups.values())
    # deleting everything must restore the mutated config's PRISTINE state
    # (the testDeletePods invariant against a freshly built instance)
    for name in sorted(h.groups):
        for bp in h.groups[name]:
            if name in fresh.affinity_groups:
                fresh.delete_allocated_pod(bp)
    h2.check_invariants(f"seed {seed} kind {kind} after full delete",
                        allow_partial_placement=True)
    # heal everything before the pristine comparison: doomed-bad binding
    # choices are path-dependent, so only the all-healthy end state is
    # deterministic (same reason test_full_delete_restores_pristine_state
    # heals first)
    empty = Harness.__new__(Harness)
    empty.algo = HivedAlgorithm(_mutated_config(kind))
    for algo in (fresh, empty.algo):
        for n in h.nodes:
            algo.add_node(Node(name=n))  # unknown (dropped-chain) = no-op
    assert h2.snapshot() == empty.snapshot(), (
        f"seed {seed} kind {kind}: state after full delete differs from a "
        f"pristine mutated-config instance"
    )
