"""Prompt prefix caching in the continuous-batching engine.

Prefix caching must be a pure prefill-FLOPs optimization: restored KV is
bit-identical to recomputation, so every test here is a differential check
against an engine with the cache disabled (the CLAUDE.md hand-rolled-copy
rule: exactness guards pin the shortcut to the canonical path).
"""

import pytest

pytest.importorskip("jax")

import jax
import jax.numpy as jnp

from hivedscheduler_tpu.models import transformer as tm
from hivedscheduler_tpu.models.serving import ServingEngine


def tiny_cfg(**kw):
    base = dict(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=128, max_seq_len=128)
    base.update(kw)
    return tm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    params = tm.cast_params(tm.init_params(cfg, jax.random.PRNGKey(0)),
                            cfg.dtype)
    return cfg, params


SYSTEM = list(range(40, 60))  # 20-token shared "system prompt"


def run_engine(cfg, params, prompts, budget=6, **kw):
    eng = ServingEngine(params, cfg, max_batch=2, max_len=96, **kw)
    reqs = [eng.submit(p, budget) for p in prompts]
    eng.run_until_drained()
    return eng, [r.tokens_out for r in reqs]


def test_prefix_hits_are_exact(setup):
    cfg, params = setup
    prompts = [SYSTEM + [7, 8, 9], SYSTEM + [11, 12], SYSTEM + [7, 8, 9, 10]]
    _, plain = run_engine(cfg, params, prompts)
    eng, cached = run_engine(cfg, params, prompts, prefix_cache_size=16)
    assert cached == plain
    # prompt 2 shares only the system prompt with prompt 1: block-granular
    # storage matches its 16-token boundary entry; prompt 3 extends prompt 1
    # wholly and reuses its full 23 tokens
    assert eng.prefix_hits == 2
    assert eng.prefix_tokens_reused == 16 + len(prompts[0])


def test_longest_prefix_wins(setup):
    cfg, params = setup
    # prompt 3 extends prompt 2 (which extends prompt 1): the match must
    # pick the longest cached prefix, not the first inserted
    p1 = SYSTEM
    p2 = SYSTEM + [70, 71, 72, 73]
    p3 = SYSTEM + [70, 71, 72, 73, 74]
    _, plain = run_engine(cfg, params, [p1, p2, p3])
    eng, cached = run_engine(cfg, params, [p1, p2, p3], prefix_cache_size=16)
    assert cached == plain
    assert eng.prefix_hits == 2
    assert eng.prefix_tokens_reused == len(p1) + len(p2)


@pytest.mark.slow  # tier-1 wall-time budget (ISSUE 15): boundary
# variant; tier-1 cousins: test_prefix_hits_are_exact +
# test_longest_prefix_wins through the same hit/extend path
def test_identical_prompt_matches_block_boundary(setup):
    cfg, params = setup
    prompts = [SYSTEM + [5], SYSTEM + [5]]
    _, plain = run_engine(cfg, params, prompts)
    eng, cached = run_engine(cfg, params, prompts, prefix_cache_size=16)
    assert cached == plain
    # strict prefix only (the tail prefill needs >= 1 token for the
    # logits): the identical 21-token prompt can't reuse its own full
    # entry, but its 16-token boundary entry matches
    assert eng.prefix_hits == 1
    assert eng.prefix_tokens_reused == 16


def test_lru_eviction_stays_exact(setup):
    cfg, params = setup
    a, b = SYSTEM, [99] * 24
    prompts = [a + [1], b + [2], a + [3], b + [4]]
    _, plain = run_engine(cfg, params, prompts)
    eng, cached = run_engine(cfg, params, prompts, prefix_cache_size=1)
    assert cached == plain
    assert len(eng._prefix_cache) == 1


def test_near_arena_end_clamp_candidates_skipped(setup):
    """A candidate whose tail prefill bucket would clamp against max_len
    must be skipped (dynamic_update_slice would silently shift the write
    and corrupt the row); a shorter boundary entry that fits is used
    instead."""
    cfg, params = setup
    long_pref = list(range(90))
    # 95-token prompt, budget 1: the 90-token candidate needs bucket(5)=8
    # past 90 -> 98 > 96, skipped; the 64-token boundary entry needs
    # bucket(31)=32 -> 96 <= 96, fits
    prompts = [long_pref, long_pref + [1, 2, 3, 4, 5]]
    eng_plain = ServingEngine(params, cfg, max_batch=1, max_len=96)
    plain = []
    for p in prompts:
        r = eng_plain.submit(p, 1)
        eng_plain.run_until_drained()
        plain.append(r.tokens_out)
    eng = ServingEngine(params, cfg, max_batch=1, max_len=96,
                        prefix_cache_size=16)
    got = []
    for p in prompts:
        r = eng.submit(p, 1)
        eng.run_until_drained()
        got.append(r.tokens_out)
    assert got == plain
    assert eng.prefix_hits == 1
    assert eng.prefix_tokens_reused == 64


@pytest.mark.slow  # tier-1 wall-time budget (ISSUE 13): steady-state
# traffic soak variant; tier-1 cousins: test_prefix_hits_are_exact +
# test_longest_prefix_wins (same restore/tail-prefill machinery under
# deterministic interleavings)
def test_staggered_mixed_traffic_exact(setup):
    """Prefix hits interleaved with decode steps of other rows (the
    continuous-batching steady state) stay exact."""
    cfg, params = setup
    prompts = [SYSTEM + [i] for i in range(5)] + [[77, 78], SYSTEM + [1, 2]]
    for size in (0, 4):
        eng = ServingEngine(params, cfg, max_batch=2, max_len=96,
                            prefix_cache_size=size)
        reqs = []
        pending = list(prompts)
        step = 0
        while pending or any(not r.done for r in reqs):
            if pending and step % 2 == 0:
                reqs.append(eng.submit(pending.pop(0), 5))
            eng.step()
            step += 1
        outs = [r.tokens_out for r in reqs]
        if size == 0:
            plain = outs
        else:
            assert outs == plain
            assert eng.prefix_hits >= 4


@pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
def test_int8_engine_prefix_exact(setup):
    """Prefix caching composes with weight-only int8 serving: the cache
    stores KV (activations), not weights, so quantization is orthogonal —
    streams must match the uncached int8 engine exactly."""
    cfg, params_bf16 = setup
    from hivedscheduler_tpu.models import quant

    qparams = quant.quantize_params(params_bf16, cfg)
    prompts = [SYSTEM + [7], SYSTEM + [9, 9], SYSTEM + [7, 5]]
    _, plain = run_engine(cfg, qparams, prompts, budget=5)
    eng, cached = run_engine(cfg, qparams, prompts, budget=5,
                             prefix_cache_size=16)
    assert cached == plain
    assert eng.prefix_hits >= 2


@pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
def test_speculative_engine_prefix_exact(setup):
    """Prefix caching composes with speculative serving: the payload carries
    target AND draft KV, so restored rows verify identically — the greedy
    stream must equal the uncached speculative engine's (itself pinned
    bit-exact to vanilla greedy by test_serving_speculative)."""
    cfg, params = setup
    from hivedscheduler_tpu.models.serving import SpeculativeServingEngine

    dcfg = tiny_cfg(n_layers=1)
    dparams = tm.cast_params(tm.init_params(dcfg, jax.random.PRNGKey(1)),
                             dcfg.dtype)
    prompts = [SYSTEM + [7, 8], SYSTEM + [9], SYSTEM + [7, 8, 3]]
    outs = {}
    for size in (0, 16):
        eng = SpeculativeServingEngine(params, cfg, dparams, dcfg, gamma=3,
                                       max_batch=2, max_len=96,
                                       prefix_cache_size=size)
        reqs = [eng.submit(p, 6) for p in prompts]
        eng.run_until_drained()
        outs[size] = [r.tokens_out for r in reqs]
    assert outs[16] == outs[0]
    assert eng.prefix_hits == 2
