"""Capacity ledger (ISSUE 14): chip-state interval accounting with the
conservation invariant, the live runtime feed (bind -> backfill admit ->
defrag evict/rebind -> bad node -> release reconstructed over HTTP with
conservation asserted at every step), the wait-ETA estimator and its
``/v1/inspect/gangs/<id>/eta`` surface, the Perfetto node lanes, the
chaos invariant, the bench differential (ledger-derived numbers pinned
to the legacy hand-rolled counters), and the overhead gate (disabled
path = one attribute check).
"""

import json
import math
import os
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tests.test_defrag import make_pod  # noqa: E402,F401
from tests.test_defrag_runtime import (  # noqa: E402
    build_scheduler,
    drive,
    fragmented_scheduler,
)

from hivedscheduler_tpu.api import constants as C  # noqa: E402
from hivedscheduler_tpu.chaos import invariants  # noqa: E402
from hivedscheduler_tpu.obs import eta as obs_eta  # noqa: E402
from hivedscheduler_tpu.obs import journal  # noqa: E402
from hivedscheduler_tpu.obs import ledger  # noqa: E402
from hivedscheduler_tpu.obs import trace as obs_trace  # noqa: E402


@pytest.fixture(autouse=True)
def _ledger_isolation():
    """Every test starts with the ledger (and journal) off and empty; the
    global singletons never leak across tests."""
    for _ in range(1):
        ledger.disable()
        ledger.LEDGER.clear()
        journal.disable()
        journal.JOURNAL.clear()
        obs_trace.disable()
        obs_trace.TRACER.clear()
    yield
    ledger.disable()
    ledger.LEDGER.clear()
    journal.disable()
    journal.JOURNAL.clear()
    obs_trace.disable()
    obs_trace.TRACER.clear()


def fresh(metrics=False):
    l = ledger.CapacityLedger(metrics=metrics)
    l.enabled = True
    return l


# ----------------------------------------------------------------- core


class TestLedgerCore:
    def test_disabled_is_noop(self):
        assert not ledger.LEDGER.enabled
        ledger.LEDGER.register_node("n0", 4)
        ledger.LEDGER.transition("n0", [0], "busy_guaranteed")
        assert ledger.LEDGER.chips() == 0

    def test_unregistered_state_rejected(self):
        l = fresh()
        with pytest.raises(ValueError,
                           match="not a registered chip state"):
            l.transition("n0", [0], "made_up_state")

    def test_intervals_accumulate_and_conserve(self):
        l = fresh()
        l.register_node("n0", 4, chain="c", at=0.0)
        l.transition("n0", [0, 1], "busy_guaranteed", vc="vc-a",
                     gang="g1", at=1.0)
        # same (state, vc, gang): the interval just continues — no churn
        l.transition("n0", [0, 1], "busy_guaranteed", vc="vc-a",
                     gang="g1", at=2.0)
        l.release("n0", [0, 1], at=5.0)
        l.settle(10.0)
        totals = l.totals(10.0)
        assert totals[("busy_guaranteed", "vc-a", "c")] == pytest.approx(8.0)
        # conservation: 4 chips x 10 units
        assert sum(totals.values()) == pytest.approx(40.0)
        assert l.conservation_gap(10.0) == pytest.approx(0.0)
        assert l.occupancy() == {"idle_free": 4}
        invariants.check_ledger(ledger=l, at=10.0)

    def test_gang_membership_and_completed_durations(self):
        l = fresh()
        l.register_node("n0", 4, at=0.0)
        l.transition("n0", [0, 1], "busy_guaranteed", vc="v",
                     gang="g", at=1.0)
        assert l.running_gangs(at=3.0) == [("g", 2, 2.0, "v")]
        l.release("n0", [0, 1], at=7.0)
        assert l.running_gangs(at=8.0) == []
        assert l.completed_durations() == [pytest.approx(6.0)]
        assert l.gang_seconds("g") == {
            "busy_guaranteed": pytest.approx(12.0)}

    def test_bad_node_shadows_and_restores(self):
        l = fresh()
        l.register_node("n0", 2, at=0.0)
        l.transition("n0", [0], "busy_guaranteed", vc="v", gang="g",
                     at=1.0)
        l.set_node_bad("n0", True, at=2.0)
        assert l.occupancy() == {"bad_hardware": 2}
        # release while bad updates the SHADOW: recovery restores idle,
        # not the stale busy state
        l.release("n0", [0], at=3.0)
        l.set_node_bad("n0", False, at=4.0)
        assert l.occupancy() == {"idle_free": 2}
        l.settle(5.0)
        totals = l.totals(5.0)
        # chip 0: idle 0-1, busy 1-2, bad 2-4 (vc kept through 3), idle 4-5
        assert totals[("busy_guaranteed", "v", "")] == pytest.approx(1.0)
        assert sum(v for (s, _v, _c), v in totals.items()
                   if s == "bad_hardware") == pytest.approx(4.0)
        assert l.conservation_gap(5.0) == pytest.approx(0.0)

    def test_reserved_holds_capture_idle_only(self):
        l = fresh()
        l.register_node("n0", 2, at=0.0)
        l.transition("n0", [0], "busy_guaranteed", vc="v", gang="g",
                     at=0.0)
        l.sync_reserved({"n0": "idle_reserved"}, at=1.0)
        # busy chip untouched; the idle one is held
        assert l.occupancy() == {"busy_guaranteed": 1, "idle_reserved": 1}
        # a chip released on a held node lands in the hold state
        l.release("n0", [0], at=2.0)
        assert l.occupancy() == {"idle_reserved": 2}
        l.sync_reserved({}, at=3.0)
        assert l.occupancy() == {"idle_free": 2}
        invariants.check_ledger(ledger=l, at=4.0)

    def test_idle_diagnosis_reclassifies_diag_states_only(self):
        l = fresh()
        l.register_node("n0", 2, at=0.0)
        l.register_node("n1", 2, at=0.0)
        l.sync_reserved({"n1": "idle_reserved"}, at=0.0)
        l.set_idle_diagnosis("idle_quota_stranded", at=1.0)
        assert l.occupancy() == {"idle_quota_stranded": 2,
                                 "idle_reserved": 2}
        with pytest.raises(ValueError, match="not an idle diagnosis"):
            l.set_idle_diagnosis("busy_guaranteed")
        l.set_idle_diagnosis("idle_free", at=2.0)
        assert l.occupancy() == {"idle_free": 2, "idle_reserved": 2}

    def test_reattribute_conserves_total(self):
        l = fresh()
        l.register_node("n0", 4, at=0.0)
        l.transition("n0", [0, 1, 2, 3], "busy_guaranteed", vc="v",
                     gang="g", at=0.0)
        l.settle(10.0)
        l.reattribute(12.0, ("busy_guaranteed", "v", ""),
                      ("migration_downtime", "v", ""))
        totals = l.totals(10.0)
        assert totals[("migration_downtime", "v", "")] == \
            pytest.approx(12.0)
        assert totals[("busy_guaranteed", "v", "")] == pytest.approx(28.0)
        assert l.conservation_gap(10.0) == pytest.approx(0.0)

    def test_probe_suppression_mutes_transitions(self):
        l = fresh()
        l.register_node("n0", 2, at=0.0)
        with journal.suppress():
            l.transition("n0", [0], "busy_guaranteed", vc="v", gang="g",
                         at=1.0)
        assert l.occupancy() == {"idle_free": 2}

    def test_snapshot_and_vc_drilldown_shapes(self):
        l = fresh()
        l.register_node("n0", 4, chain="c", at=0.0)
        l.transition("n0", [0, 1], "busy_guaranteed", vc="vc-a",
                     gang="g", at=1.0)
        snap = l.snapshot(at=3.0)
        assert snap["chips"] == 4
        assert set(snap["states"]) == set(ledger.CHIP_STATES)
        assert snap["states"]["busy_guaranteed"]["chips"] == 2
        assert snap["conservationGapChipSeconds"] == pytest.approx(0.0)
        assert snap["byVc"]["vc-a"]["busy_guaranteed"] == \
            pytest.approx(4.0)
        vc = l.vc_snapshot("vc-a", at=3.0)
        assert vc["chipsNow"] == 2
        assert vc["gangs"] == [{"gang": "g", "chips": 2, "ageS": 2.0}]
        json.dumps(snap), json.dumps(vc)  # JSON-serializable


# ----------------------------------------------------- wait-ETA estimator


class TestEtaEstimator:
    def test_idle_now(self):
        f = obs_eta.estimate("w", 4, idle_chips=8, running=[])
        assert f.eta_s == 0.0 and f.basis == "idle-now"

    def test_release_projection_orders_completions(self):
        f = obs_eta.estimate(
            "w", 6, idle_chips=0,
            running=[("a", 4, 1.0, "v"), ("b", 4, 9.0, "v")],
            completed_durations=[10.0])
        # b is 9 into an expected 10 -> frees at 1; a frees at 9
        assert f.basis == "release-projection"
        assert f.eta_s == pytest.approx(9.0)
        assert f.projected_releases == 2

    def test_overdue_gang_gets_half_expectation(self):
        f = obs_eta.estimate("w", 4, idle_chips=0,
                             running=[("a", 4, 99.0, "v")],
                             completed_durations=[10.0])
        assert f.eta_s == pytest.approx(5.0)

    def test_reservation_ttl_counts_as_release(self):
        f = obs_eta.estimate("w", 4, idle_chips=0, running=[],
                             reserved=[(7.5, 4)],
                             completed_durations=[10.0])
        assert f.basis == "release-projection"
        assert f.eta_s == pytest.approx(7.5)

    def test_horizon_fallback_is_finite(self):
        f = obs_eta.estimate("w", 10_000, idle_chips=0,
                             running=[("a", 4, 0.0, "v")],
                             completed_durations=[10.0])
        assert f.basis == "horizon-fallback"
        assert math.isfinite(f.eta_s) and f.eta_s > 0

    def test_waiters_own_degraded_incarnation_excluded(self):
        f = obs_eta.estimate("w", 4, idle_chips=0,
                             running=[("w", 2, 0.0, "v")],
                             completed_durations=[10.0])
        assert f.basis == "horizon-fallback"

    def test_record_journals_forecast(self):
        journal.enable()
        f = obs_eta.estimate("w", 4, idle_chips=8, running=[])
        obs_eta.record(f)
        events = journal.JOURNAL.snapshot()
        assert [e.type for e in events] == ["eta_forecast"]
        assert events[0].args["basis"] == "idle-now"


# --------------------------------------------- the full episode over HTTP


def _serve(sched):
    from hivedscheduler_tpu.webserver import WebServer

    server = WebServer(sched, address="127.0.0.1:0")
    host, port = server.async_run()
    return server, f"http://{host}:{port}"


def _get(base, path):
    with urllib.request.urlopen(base + path) as r:
        return r.status, json.loads(r.read())


def _check(ctx):
    invariants.check_ledger(ctx=ctx)


class TestRuntimeEpisode:
    def test_bind_backfill_defrag_badnode_release_conserves(self):
        """The full episode: bind -> wait -> defrag plan (reserve+evict)
        -> rebind -> backfill admit -> bad node -> release, with the
        conservation invariant asserted at every step and the HTTP
        surface read along the way."""
        journal.enable()
        ledger.enable()
        sched, kube, nodes = fragmented_scheduler()
        _check("post-frag")
        assert ledger.LEDGER.chips() == 8
        occ = ledger.LEDGER.occupancy()
        assert occ == {"busy_guaranteed": 4, "idle_free": 4}

        # a 4-chip waiter: fragmentation diagnosis lands on idle chips
        w = make_pod("w-0", "w", 4)
        assert drive(sched, kube, nodes, w) is None
        _check("post-wait")
        assert ledger.LEDGER.occupancy() == {"busy_guaranteed": 4,
                                             "idle_fragmented": 4}

        # plan: waiter slice reserved (idle_reserved), mover target held
        # (migration_downtime), mover evicted
        plan = sched.defrag_tick()["planned"]
        assert plan is not None
        _check("post-plan")
        occ = ledger.LEDGER.occupancy()
        assert occ["idle_reserved"] == 4
        assert occ.get("migration_downtime", 0) == 2
        assert occ["busy_guaranteed"] == 2

        # an opportunistic rider admitted INTO the hold is busy_backfill
        server, base = _serve(sched)
        try:
            rider = make_pod("r-0", "r", 2, prio=-1)
            assert drive(sched, kube, nodes, rider) is not None
            _check("post-backfill")
            assert ledger.LEDGER.occupancy().get("busy_backfill") == 2
            kube.delete_pod("default", "r-0")
            _check("post-backfill-release")

            sched.resume_migrations()
            _check("post-rebind")
            assert drive(sched, kube, nodes, w) is not None
            _check("post-waiter-bind")
            assert ledger.LEDGER.occupancy() == {"busy_guaranteed": 8}

            # HTTP: the capacity snapshot + per-VC drilldown
            status, snap = _get(base, C.CAPACITY_PATH)
            assert status == 200 and snap["enabled"]
            assert snap["chips"] == 8
            assert abs(snap["conservationGapChipSeconds"]) < 1e-6
            assert snap["states"]["busy_guaranteed"]["chips"] == 8
            status, vc = _get(base, C.CAPACITY_PATH + "/vc-x")
            assert status == 200 and vc["chipsNow"] == 8
            assert {g["gang"] for g in vc["gangs"]} >= {"w"}

            # a new waiter gets a finite ETA over HTTP, journaled
            w2 = make_pod("w2-0", "w2", 4)
            assert drive(sched, kube, nodes, w2) is None
            status, f = _get(base, C.GANGS_PATH + "/w2/eta")
            assert status == 200
            assert math.isfinite(f["etaS"]) and f["needChips"] == 4
            assert f["basis"] in ("idle-now", "release-projection",
                                  "horizon-fallback")
            tl = journal.JOURNAL.timeline("w2")
            assert "eta_forecast" in [e["type"] for e in tl["events"]]
            # unknown gang -> 404
            with pytest.raises(urllib.error.HTTPError) as exc:
                _get(base, C.GANGS_PATH + "/nope/eta")
            assert exc.value.code == 404
        finally:
            server.stop()

        # bad node: chips burn as bad_hardware, recovery restores busy
        bad = sorted(ledger.LEDGER._nodes)[0]
        from hivedscheduler_tpu.k8s.types import Node
        kube.update_node(Node(name=bad, unschedulable=True))
        _check("post-bad")
        assert ledger.LEDGER.occupancy()["bad_hardware"] == 4
        kube.update_node(Node(name=bad))
        _check("post-recover")
        assert ledger.LEDGER.occupancy() == {"busy_guaranteed": 8}

        # release the waiter: its 4 chips return to idle (w2 still waits,
        # so they carry its diagnosis) and conservation holds
        kube.delete_pod("default", "w-0")
        _check("post-release")
        occ = ledger.LEDGER.occupancy()
        assert sum(occ.values()) == 8
        assert occ["busy_guaranteed"] == 4  # g3 + the rebound mover
        # the released/evicted gangs fed the completed-duration ring
        assert ledger.LEDGER.completed_durations()

    def test_metrics_surface(self):
        from hivedscheduler_tpu.runtime.metrics import REGISTRY

        journal.enable()
        ledger.enable()
        sched, kube, nodes = build_scheduler()
        assert drive(sched, kube, nodes, make_pod("g1-0", "g1", 4))
        kube.delete_pod("default", "g1-0")
        text = REGISTRY.render()
        assert 'tpu_hive_chip_seconds_total{state="busy_guaranteed"' in text
        assert 'tpu_hive_chip_state_chips{state="idle_free"}' in text

    def test_recovery_replay_is_idempotent(self):
        """A crash-restarted scheduler re-registers the same chips and
        replays bound pods through add_allocated_pod: same-state
        transitions continue intervals, conservation holds."""
        from hivedscheduler_tpu.runtime.scheduler import HivedScheduler
        from tests.test_defrag import mini_config

        journal.enable()
        ledger.enable()
        sched, kube, nodes = build_scheduler()
        assert drive(sched, kube, nodes, make_pod("g1-0", "g1", 4))
        _check("pre-restart")
        # "crash": the old scheduler's informers stop delivering
        kube._node_handlers.clear()
        kube._pod_handlers.clear()
        sched2 = HivedScheduler(mini_config(), kube)
        sched2.start()
        _check("post-restart")
        assert ledger.LEDGER.chips() == 8
        assert ledger.LEDGER.occupancy()["busy_guaranteed"] == 4


# ------------------------------------------------------ chaos invariant


class TestCheckLedger:
    def test_noop_when_disabled(self):
        invariants.check_ledger()  # must not raise

    def test_conservation_break_flagged(self):
        l = fresh()
        l.register_node("n0", 4, at=0.0)
        with l._lock:
            l._acc[("busy_guaranteed", "v", "")] = 123.0  # leaked seconds
        with pytest.raises(invariants.InvariantViolation,
                           match="ledger conservation broken"):
            invariants.check_ledger(ledger=l, at=10.0)

    def test_unregistered_bucket_state_flagged(self):
        l = fresh()
        l.register_node("n0", 1, at=0.0)
        with l._lock:
            l._acc[("zombie_state", "", "")] = 0.0
        with pytest.raises(invariants.InvariantViolation,
                           match="unregistered chip state"):
            invariants.check_ledger(ledger=l, at=1.0)

    def test_occupancy_break_flagged(self):
        l = fresh()
        l.register_node("n0", 2, at=0.0)
        with l._lock:
            l._occ["idle_free"] = 1  # a chip in zero states
        with pytest.raises(invariants.InvariantViolation,
                           match="zero or two states"):
            invariants.check_ledger(ledger=l, at=0.0)


# ------------------------------------------------------- Perfetto merge


class TestPerfettoMerge:
    def test_node_lanes_merge_into_chrome_export(self):
        from helpers import validate_chrome_trace

        obs_trace.enable()
        ledger.enable()
        ledger.LEDGER.register_node("n0", 4)
        ledger.LEDGER.transition("n0", [0, 1, 2], "busy_guaranteed",
                                 vc="v", gang="g")
        trace_obj = obs_trace.to_chrome_trace()
        events = validate_chrome_trace(trace_obj)
        lanes = [e for e in events if e["ph"] == "M"
                 and e["args"].get("name") == "node n0"]
        assert lanes, "each node must get a named Perfetto lane"
        spans = [e["name"] for e in events if e.get("cat") == "ledger"]
        assert "state:idle_free" in spans
        assert "state:busy_guaranteed" in spans  # the dominant state now

    def test_disabled_ledger_leaves_export_unchanged(self):
        obs_trace.enable()
        before = obs_trace.to_chrome_trace()["traceEvents"]
        after = obs_trace.to_chrome_trace()["traceEvents"]
        assert [e["name"] for e in before] == [e["name"] for e in after]


# -------------------------------------------------------- overhead gate


class TestOverheadGate:
    def test_disabled_path_takes_no_lock(self):
        """The obs contract: disabled mutators are ONE attribute check —
        they must return before ever touching the lock."""
        l = ledger.LEDGER
        saved = l._lock
        l._lock = None  # any lock acquisition would raise AttributeError
        try:
            for _ in range(1000):
                l.register_node("n0", 4)
                l.transition("n0", [0], "busy_guaranteed")
                l.release("n0", [0])
                l.set_node_bad("n0", True)
                l.sync_reserved({"n0": "idle_reserved"})
        finally:
            l._lock = saved
        assert l.chips() == 0

    def test_schedule_hot_path_touches_nothing_while_disabled(self):
        sched, kube, nodes = build_scheduler()
        drive(sched, kube, nodes, make_pod("g1-0", "g1", 4))
        assert ledger.LEDGER.chips() == 0

    def test_enabled_bounded_cost(self):
        l = fresh()
        l.register_node("n0", 8, at=0.0)
        t0 = time.perf_counter()
        n = 20000
        for i in range(n):
            l.transition("n0", [i % 8],
                         "busy_guaranteed" if i % 2 else
                         "busy_opportunistic",
                         vc="v", gang=f"g{i % 16}", at=float(i))
        dt = time.perf_counter() - t0
        assert dt < 5.0, f"{n} enabled transitions took {dt:.2f}s"
        invariants.check_ledger(ledger=l, at=float(n))


# ----------------------------------------------- bench differential + CLI


class TestBenchDifferential:
    def test_ledger_derived_numbers_pin_to_legacy_counters(self):
        """replay_trace asserts ledger busy/wasted/overhead equal to the
        hand-rolled counters internally; here the artifact fields are
        checked: conservation gap ~0, attribution sums to ~1, a finite
        ETA per waiting gang."""
        import bench

        t = bench.run_trace(n_jobs=80, seed=11)
        assert t["ledger_conservation_gap"] == pytest.approx(0.0, abs=1e-3)
        shares = t["capacity_attribution"]
        assert abs(sum(shares.values()) - 1.0) < 0.01
        assert set(shares) <= set(ledger.CHIP_STATES)
        eta = t["eta"]
        assert eta["forecasts"] == eta["scored"] + eta["unresolved"]
        if eta["scored"]:
            assert math.isfinite(eta["mean_abs_err_t"])

    def test_ledger_kill_switch_reports_legacy_only(self, monkeypatch):
        import bench

        monkeypatch.setenv("HIVED_LEDGER", "0")
        t = bench.run_trace(n_jobs=40, seed=11)
        assert "capacity_attribution" not in t and "eta" not in t
        assert t["utilization_pct"] > 0


class TestCliFlags:
    def test_scheduler_cli_parses_capacity_dump(self):
        import subprocess

        proc = subprocess.run(
            [sys.executable, "-m", "hivedscheduler_tpu.cli", "--help"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0 and "--capacity-dump" in proc.stdout

    def test_capacity_dump_payload_parses(self, tmp_path):
        """The --capacity-dump payload is the snapshot JSON; smoke the
        write+parse round trip the CLI performs at shutdown."""
        ledger.enable()
        ledger.LEDGER.register_node("n0", 4)
        path = tmp_path / "capacity.json"
        with open(path, "w") as f:
            json.dump(ledger.LEDGER.snapshot(), f)
        snap = json.loads(path.read_text())
        assert snap["chips"] == 4 and set(snap["states"]) == \
            set(ledger.CHIP_STATES)
