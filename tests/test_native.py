"""Differential tests: the C++ placement search must pick exactly the same
cells as the pure-Python backtracking search, including under adversarial
fragmentation on large single-node cells."""

import random

import pytest

from hivedscheduler_tpu import native
from hivedscheduler_tpu.api.config import Config, new_config
from hivedscheduler_tpu.api.types import (
    CellTypeSpec,
    MeshLevelSpec,
    MeshSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    VirtualClusterSpec,
)
from hivedscheduler_tpu.algorithm.config_parser import parse_config
from hivedscheduler_tpu.algorithm.constants import FREE_PRIORITY
from hivedscheduler_tpu.algorithm import topology_aware as ta

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def big_node():
    """One 64-chip single-host cell (a 4x4x4 slice exposed as one K8s node),
    with intermediate levels to make affinity non-trivial."""
    mesh = MeshSpec(
        topology=(4, 4, 4),
        chip_type="chip",
        host_shape=(4, 4, 4),
        levels=[
            MeshLevelSpec(name="m2", shape=(2, 2, 1)),
            MeshLevelSpec(name="m4", shape=(2, 2, 2)),
            MeshLevelSpec(name="m16", shape=(4, 2, 2)),
            MeshLevelSpec(name="m32", shape=(4, 4, 2)),
        ],
    )
    cfg = new_config(
        Config(
            physical_cluster=PhysicalClusterSpec(
                cell_types={"slice64": CellTypeSpec(mesh=mesh)},
                physical_cells=[PhysicalCellSpec(cell_type="slice64", cell_address="n0")],
            ),
            virtual_clusters={"vc": VirtualClusterSpec()},
        )
    )
    parsed = parse_config(cfg)
    full = parsed.physical_full_list["slice64"]
    node = full[max(full)][0]
    levels = {lv.level: lv.leaf_cell_number for lv in parsed.chain_levels["slice64"]}
    return node, levels


def _forced(node, avail, num, levels, threshold, direct):
    import os
    saved_threshold = ta._NATIVE_THRESHOLD
    saved_env = os.environ.get("HIVED_DIRECT")
    ta._NATIVE_THRESHOLD = threshold
    os.environ["HIVED_DIRECT"] = "1" if direct else "0"
    try:
        return ta.find_leaf_cells_in_node(node, num, 0, list(avail), levels)
    finally:
        ta._NATIVE_THRESHOLD = saved_threshold
        if saved_env is None:
            os.environ.pop("HIVED_DIRECT", None)
        else:
            os.environ["HIVED_DIRECT"] = saved_env


def _py(node, avail, num, levels):
    # force the legacy Python backtracking branch
    return _forced(node, avail, num, levels, threshold=10**9, direct=False)


def native_search(node, avail, num, levels):
    return _forced(node, avail, num, levels, threshold=0, direct=False)


def direct_search(node, avail, num, levels):
    # the round-3 path: direct aligned-enclosure enumeration (forced on
    # regardless of candidate count)
    return _forced(node, avail, num, levels, threshold=0, direct=True)


@pytest.mark.parametrize("num", [1, 2, 4, 8, 16])
def test_differential_full_node(num):
    node, levels = big_node()
    leaves = []

    def collect(c):
        if c.level == 1:
            leaves.append(c)
        else:
            for cc in c.children:
                collect(cc)

    collect(node)
    py_picked, _ = _py(node, leaves, num, levels)
    nat_picked, _ = native_search(node, leaves, num, levels)
    assert [c.address for c in py_picked] == [c.address for c in nat_picked]


@pytest.mark.parametrize("seed", range(8))
def test_differential_fragmented(seed):
    """Random subsets of free chips (fragmentation) at random request sizes."""
    rng = random.Random(seed)
    node, levels = big_node()
    leaves = []

    def collect(c):
        if c.level == 1:
            leaves.append(c)
        else:
            for cc in c.children:
                collect(cc)

    collect(node)
    avail = [c for c in leaves if rng.random() < 0.6]
    num = rng.choice([1, 2, 3, 4, 5, 8])
    if len(avail) < num:
        return
    py_picked, py_rest = _py(node, avail, num, levels)
    nat_picked, nat_rest = native_search(node, avail, num, levels)
    assert [c.address for c in py_picked] == [c.address for c in nat_picked]
    assert [c.address for c in py_rest] == [c.address for c in nat_rest]


def test_native_speedup_adversarial_fragmentation():
    """Worst case for the backtracking search: one chip removed from every
    8-chip sub-cube, so an 8-chip request can never reach level-3 affinity and
    the search must prove the best is level 4. The C++ path must win big
    (typically ~80x) and pick identical cells."""
    import time

    node, levels = big_node()
    leaves = []

    def collect(c):
        if c.level == 1:
            leaves.append(c)
        else:
            for cc in c.children:
                collect(cc)

    collect(node)
    blocks = {}
    for leaf in leaves:
        key = tuple(o // 2 for o in leaf.mesh_origin)
        blocks.setdefault(key, []).append(leaf)
    avail = []
    for blk in blocks.values():
        avail.extend(blk[1:])  # drop one chip per 8-block

    t0 = time.perf_counter()
    py_picked, _ = _py(node, avail, 8, levels)
    t_py = time.perf_counter() - t0
    t0 = time.perf_counter()
    nat_picked, _ = native_search(node, avail, 8, levels)
    t_nat = time.perf_counter() - t0
    assert [c.address for c in py_picked] == [c.address for c in nat_picked]
    assert t_nat < t_py / 5, (t_nat, t_py)


def _collect_leaves(node):
    leaves = []

    def collect(c):
        if c.level == 1:
            leaves.append(c)
        else:
            for cc in c.children:
                collect(cc)

    collect(node)
    return leaves


@pytest.mark.parametrize("seed", range(10))
def test_differential_direct_vs_backtracking(seed):
    """Round-3 mesh-direct search: the direct aligned-enclosure enumeration
    must pick exactly the same cells (and leave the same remainder) as the
    reference backtracking search, across random fragmentation patterns and
    request sizes."""
    rng = random.Random(1000 + seed)
    node, levels = big_node()
    leaves = _collect_leaves(node)
    avail = [c for c in leaves if rng.random() < rng.choice([0.3, 0.6, 0.9])]
    # larger requests explode the backtracking REFERENCE (the very cost the
    # direct path removes); keep CI affordable and cover size via the
    # adversarial test below
    num = rng.choice([1, 2, 3, 4, 5, 6, 8])
    if len(avail) < num:
        return
    py_picked, py_rest = _py(node, avail, num, levels)
    d_picked, d_rest = direct_search(node, avail, num, levels)
    assert [c.address for c in py_picked] == [c.address for c in d_picked]
    assert [c.address for c in py_rest] == [c.address for c in d_rest]


def test_direct_beats_backtracking_adversarial():
    """The direct enumeration is near-linear: on the same adversarial
    fragmentation that makes the backtracking search prove optimality by
    exhaustion, it must beat the pure-Python backtracking by >100x while
    picking identical cells (it replaces even the C++ accelerated path on
    the hot path)."""
    import time

    node, levels = big_node()
    leaves = _collect_leaves(node)
    blocks = {}
    for leaf in leaves:
        key = tuple(o // 2 for o in leaf.mesh_origin)
        blocks.setdefault(key, []).append(leaf)
    avail = []
    for blk in blocks.values():
        avail.extend(blk[1:])  # drop one chip per 8-block

    t0 = time.perf_counter()
    py_picked, _ = _py(node, avail, 8, levels)
    t_py = time.perf_counter() - t0
    t0 = time.perf_counter()
    d_picked, _ = direct_search(node, avail, 8, levels)
    t_direct = time.perf_counter() - t0
    assert [c.address for c in py_picked] == [c.address for c in d_picked]
    assert t_direct < t_py / 100, (t_direct, t_py)


# ---------------------------------------------------------------------------
# cross-node packing: hived_find_nodes_for_pods parity (perf PR)
# ---------------------------------------------------------------------------


def _packing_cluster():
    """A multi-node cluster view: one 256-chip pod of 64 4-chip hosts."""
    mesh = MeshSpec(
        topology=(8, 8, 4),
        chip_type="chip",
        host_shape=(2, 2, 1),
        levels=[
            MeshLevelSpec(name="m8", shape=(2, 2, 2)),
            MeshLevelSpec(name="m16", shape=(4, 2, 2)),
            MeshLevelSpec(name="m32", shape=(4, 4, 2)),
            MeshLevelSpec(name="m64", shape=(4, 4, 4)),
            MeshLevelSpec(name="m128", shape=(8, 4, 4)),
        ],
    )
    cfg = new_config(
        Config(
            physical_cluster=PhysicalClusterSpec(
                cell_types={"pod256": CellTypeSpec(mesh=mesh)},
                physical_cells=[
                    PhysicalCellSpec(cell_type="pod256", cell_address="p0")
                ],
            ),
            virtual_clusters={"vc": VirtualClusterSpec()},
        )
    )
    parsed = parse_config(cfg)
    ccl = parsed.physical_full_list["pod256"]
    levels = {lv.level: lv.leaf_cell_number
              for lv in parsed.chain_levels["pod256"]}
    return ccl, levels


@pytest.mark.parametrize("seed", range(6))
def test_prefix_fit_exact_vs_reference_walk(seed):
    """hived_find_nodes_prefix must return EXACTLY the largest descending-
    flat prefix whose ascending reading packs (two-phase: opportunistic
    then the request priority), matching a brute-force walk that probes
    every take through the pure-Python _find_nodes — across randomized
    load/health/suggested churn."""
    import random as _random

    from hivedscheduler_tpu.algorithm.cell_allocation import (
        allocate_cell_walk,
        release_cell_walk,
    )
    from hivedscheduler_tpu.algorithm.constants import OPPORTUNISTIC_PRIORITY

    if not native.prefix_available():
        pytest.skip("native prefix entry unavailable")
    rng = _random.Random(2000 + seed)
    ccl, levels = _packing_cluster()
    s_nat = ta.TopologyAwareScheduler(ccl, levels, cross_priority_pack=False)
    s_py = ta.TopologyAwareScheduler(ccl, levels, cross_priority_pack=False)
    s_py._native_pack = False  # pure-Python reference feasibility walk
    assert s_nat._native_pack_state() is not None

    leaves = ccl[1]
    all_nodes = sorted({c.nodes[0] for c in leaves})
    allocated = []
    for step in range(25):
        if allocated and rng.random() < 0.45:
            for _ in range(rng.randint(1, 8)):
                if not allocated:
                    break
                c, p = allocated.pop(rng.randrange(len(allocated)))
                release_cell_walk(c, p)
        else:
            for _ in range(rng.randint(1, 8)):
                c = leaves[rng.randrange(len(leaves))]
                p = rng.choice([-1, 0, 5])
                allocate_cell_walk(c, p)
                allocated.append((c, p))
        if rng.random() < 0.3:
            c = leaves[rng.randrange(len(leaves))]
            c.set_healthiness("Bad" if c.healthy else "Healthy")
        ignore = rng.random() < 0.5
        suggested = (set() if ignore else
                     set(rng.sample(all_nodes,
                                    rng.randint(len(all_nodes) // 2,
                                                len(all_nodes)))))
        # descending member sizes, as the relax walk's flat segment
        flat = sorted(
            (rng.choice([4, 4, 4, 8, 16]) for _ in range(rng.randint(1, 40))),
            reverse=True)
        p = rng.choice([-1, 5])
        got = s_nat.max_feasible_prefix(flat, p, suggested, ignore)

        def feasible(take):
            nums = sorted(flat[:take])
            for prio in ([OPPORTUNISTIC_PRIORITY] if p < 0
                         else [OPPORTUNISTIC_PRIORITY, p]):
                s_py._update_cluster_view(prio, suggested, ignore)
                picked, _ = s_py._find_nodes(nums, True)
                if picked is not None:
                    return True
            return False

        want = 0
        for take in range(len(flat), 0, -1):
            if feasible(take):
                want = take
                break
        assert got == want, (step, flat, got, want)
        # keep the two views' sort histories in lockstep for the next step
        for s in (s_nat, s_py):
            s._update_cluster_view(
                OPPORTUNISTIC_PRIORITY, suggested, ignore)
            s._find_nodes([4], True)


# ---------------------------------------------------------------------------
# multi-chain relax parity: native prefix walk vs HIVED_NATIVE=0 reference
# ---------------------------------------------------------------------------


def _two_chain_config():
    """Two 128-chip v5p chains (32 hosts each — above the native packing
    threshold on both the physical and the fully-assigned VC views) sharing
    one leaf cell type, so an oversized vc-r gang must relax across chains."""
    from hivedscheduler_tpu.api.types import VirtualCellSpec

    def mesh(prefix):
        return MeshSpec(
            topology=(8, 4, 4), chip_type="v5p-chip", host_shape=(2, 2, 1),
            levels=[
                MeshLevelSpec(name=f"{prefix}-8", shape=(2, 2, 2)),
                MeshLevelSpec(name=f"{prefix}-16", shape=(4, 2, 2)),
                MeshLevelSpec(name=f"{prefix}-32", shape=(4, 4, 2)),
                MeshLevelSpec(name=f"{prefix}-64", shape=(4, 4, 4)),
            ],
        )

    return new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={"chainA": CellTypeSpec(mesh=mesh("a")),
                        "chainB": CellTypeSpec(mesh=mesh("b"))},
            physical_cells=[
                PhysicalCellSpec(cell_type="chainA", cell_address="pa"),
                PhysicalCellSpec(cell_type="chainB", cell_address="pb"),
            ],
        ),
        virtual_clusters={
            "vc-r": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=2, cell_type="chainA.a-64"),
                VirtualCellSpec(cell_number=2, cell_type="chainB.b-64"),
            ]),
        },
    ))


def _relax_churn(seed: int, py_reference: bool):
    """Drive one seeded gang churn (multi-chain relaxation reachable)
    through a fresh HivedAlgorithm; returns the per-step decision log:
    placements at chip granularity and failure strings."""
    import os as _os
    import random as _random

    from hivedscheduler_tpu.algorithm.hived import HivedAlgorithm
    from hivedscheduler_tpu.common.utils import to_json
    from hivedscheduler_tpu.k8s.types import Container, Node, Pod
    from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
    from hivedscheduler_tpu.runtime.utils import new_binding_pod
    from hivedscheduler_tpu.api import constants as C

    saved = _os.environ.get("HIVED_NATIVE")
    if py_reference:
        _os.environ["HIVED_NATIVE"] = "0"
    try:
        _random.seed(seed)
        rng = _random.Random(seed)
        algo = HivedAlgorithm(_two_chain_config())
        nodes = sorted({n for ccl in algo.full_cell_list.values()
                        for c in ccl[max(ccl)] for n in c.nodes})
        for n in nodes:
            algo.add_node(Node(name=n))
        log = []
        groups = {}
        gid = 0
        bad = set()
        for step in range(30):
            op = rng.random()
            if op < 0.2 and groups:
                name = rng.choice(sorted(groups))
                for bp in groups.pop(name):
                    algo.delete_allocated_pod(bp)
                log.append(("free", name))
                continue
            if op < 0.3:
                n = rng.choice(nodes)
                if n in bad:
                    bad.discard(n)
                    algo.update_node(
                        Node(name=n, conditions=[]), Node(name=n))
                else:
                    from hivedscheduler_tpu.k8s.types import NodeCondition
                    bad.add(n)
                    algo.update_node(Node(name=n), Node(
                        name=n,
                        conditions=[NodeCondition(type="Ready",
                                                  status="False")]))
                log.append(("flip", n))
                continue
            # schedule a gang; ~half are too big for one chain (relax)
            pods = rng.choice([2, 4, 8, 20, 24, 36, 40])
            prio = rng.choice([-1, 5])
            name = f"rg{gid}"
            gid += 1
            spec = {
                "virtualCluster": "vc-r", "priority": prio,
                "leafCellType": "v5p-chip", "leafCellNumber": 4,
                "multiChainRelaxPolicy": rng.choice(["fewest", "balanced"]),
                "affinityGroup": {
                    "name": name,
                    "members": [{"podNumber": pods, "leafCellNumber": 4}],
                },
            }
            bound = []
            ok = True
            outcome = None
            for i in range(pods):
                pod = Pod(
                    name=f"{name}-{i}", uid=f"{name}-{i}",
                    annotations={C.ANNOTATION_POD_SCHEDULING_SPEC:
                                 to_json(spec)},
                    containers=[Container(resource_limits={
                        C.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1})],
                )
                r = algo.schedule(pod, nodes, FILTERING_PHASE)
                if r.pod_bind_info is None:
                    outcome = (
                        "wait",
                        r.pod_wait_info.reason
                        if r.pod_wait_info is not None else "",
                        tuple(sorted(
                            (v.uid for v
                             in r.pod_preempt_info.victim_pods)))
                        if r.pod_preempt_info is not None else (),
                    )
                    ok = False
                    break
                bp = new_binding_pod(pod, r.pod_bind_info)
                algo.add_allocated_pod(bp)
                bound.append(bp)
                outcome = ("bind", tuple(sorted(
                    (m.physical_node,
                     tuple(m.physical_leaf_cell_indices))
                    for gms in r.pod_bind_info.affinity_group_bind_info
                    for m in gms.pod_placements)))
                log.append(("pod", f"{name}-{i}") + outcome)
            if ok:
                groups[name] = bound
            else:
                for bp in bound:
                    algo.delete_allocated_pod(bp)
                log.append(("gang-fail", name) + (outcome or ()))
        return log
    finally:
        if saved is None:
            _os.environ.pop("HIVED_NATIVE", None)
        else:
            _os.environ["HIVED_NATIVE"] = saved


@pytest.mark.parametrize("seed", range(4))
def test_multi_chain_relax_native_parity(seed):
    """The PR 4 single-chain pin, extended to multi-chain clusters: gang
    churn whose oversized gangs relax across two chains must produce
    bit-equal placements (node + chip indices) and byte-identical failure
    strings with the native prefix walk engaged vs HIVED_NATIVE=0 —
    across load/health churn and both relax policies."""
    if not native.prefix_available():
        pytest.skip("native prefix entry unavailable")
    ref = _relax_churn(seed, py_reference=True)
    fast = _relax_churn(seed, py_reference=False)
    assert ref == fast


def test_multi_chain_relax_prefix_bound_non_vacuous():
    """The parity above would be vacuous if the native prefix walk never
    engaged or never pruned a take: pin that the two-chain churn really
    routes through max_feasible_prefix and skips provably-unpackable
    prefixes."""
    if not native.prefix_available():
        pytest.skip("native prefix entry unavailable")
    calls = {"n": 0, "pruned": 0}
    orig = ta.TopologyAwareScheduler.max_feasible_prefix

    def spy(self, flat, p, sugg, ign):
        r = orig(self, flat, p, sugg, ign)
        calls["n"] += 1
        if r < len(flat):
            calls["pruned"] += 1
        return r

    ta.TopologyAwareScheduler.max_feasible_prefix = spy
    try:
        _relax_churn(0, py_reference=False)
    finally:
        ta.TopologyAwareScheduler.max_feasible_prefix = orig
    assert calls["n"] > 0 and calls["pruned"] > 0, calls


@pytest.mark.parametrize("seed", range(6))
def test_packing_native_vs_python_parity(seed):
    """HIVED_NATIVE=0 vs native parity for the cross-node packing entry
    point: two schedulers over the SAME cells — one using the one-call C
    packing (sort + enclosure pass + greedy), one forced onto the Python
    incremental path — must pick IDENTICAL nodes and produce byte-identical
    failure reasons across randomized load, health and suggested-node
    churn. Both maintain their own persistent sort order from the same
    seed, so strict equality (not just score equality) is the contract."""
    import random as _random

    from hivedscheduler_tpu.algorithm.cell_allocation import (
        allocate_cell_walk,
        release_cell_walk,
    )
    from hivedscheduler_tpu.algorithm import topology_aware as ta

    if not native.pack_available():
        pytest.skip("native packing entry unavailable")
    rng = _random.Random(seed)
    ccl, levels = _packing_cluster()
    s_nat = ta.TopologyAwareScheduler(ccl, levels, cross_priority_pack=False)
    s_py = ta.TopologyAwareScheduler(ccl, levels, cross_priority_pack=False)
    s_py._native_pack = False  # force the Python incremental reference
    assert s_nat._native_pack_state() is not None, "native packing not engaged"

    leaves = ccl[1]
    all_nodes = sorted({c.nodes[0] for c in leaves})
    allocated = []
    for step in range(40):
        # churn: allocate or release random leaves at random priorities
        if allocated and rng.random() < 0.45:
            for _ in range(rng.randint(1, 8)):
                if not allocated:
                    break
                c, p = allocated.pop(rng.randrange(len(allocated)))
                release_cell_walk(c, p)
        else:
            for _ in range(rng.randint(1, 8)):
                c = leaves[rng.randrange(len(leaves))]
                p = rng.choice([-1, 0, 5])
                allocate_cell_walk(c, p)
                allocated.append((c, p))
        # health churn
        if rng.random() < 0.3:
            c = leaves[rng.randrange(len(leaves))]
            c.set_healthiness("Bad" if c.healthy else "Healthy")
        ignore = rng.random() < 0.5
        if ignore:
            suggested = set()
        else:
            suggested = set(rng.sample(all_nodes,
                                       rng.randint(0, len(all_nodes))))
        nums = rng.choice([[4], [4, 4], [4] * 8, [8] * 4, [4] * 64,
                           [16] * 2, [4] * 63 + [8]])
        p = rng.choice([-1, 5])
        for s in (s_nat, s_py):
            s._update_cluster_view(p, suggested, ignore)
        picked_nat, reason_nat = s_nat._find_nodes(sorted(nums), True)
        picked_py, reason_py = s_py._find_nodes(sorted(nums), True)
        assert picked_nat == picked_py, (step, nums, picked_nat, picked_py)
        assert reason_nat == reason_py, (step, nums, reason_nat, reason_py)
        assert s_nat._order == s_py._order, step
