"""Differential tests: the C++ placement search must pick exactly the same
cells as the pure-Python backtracking search, including under adversarial
fragmentation on large single-node cells."""

import random

import pytest

from hivedscheduler_tpu import native
from hivedscheduler_tpu.api.config import Config, new_config
from hivedscheduler_tpu.api.types import (
    CellTypeSpec,
    MeshLevelSpec,
    MeshSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    VirtualClusterSpec,
)
from hivedscheduler_tpu.algorithm.config_parser import parse_config
from hivedscheduler_tpu.algorithm.constants import FREE_PRIORITY
from hivedscheduler_tpu.algorithm import topology_aware as ta

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def big_node():
    """One 64-chip single-host cell (a 4x4x4 slice exposed as one K8s node),
    with intermediate levels to make affinity non-trivial."""
    mesh = MeshSpec(
        topology=(4, 4, 4),
        chip_type="chip",
        host_shape=(4, 4, 4),
        levels=[
            MeshLevelSpec(name="m2", shape=(2, 2, 1)),
            MeshLevelSpec(name="m4", shape=(2, 2, 2)),
            MeshLevelSpec(name="m16", shape=(4, 2, 2)),
            MeshLevelSpec(name="m32", shape=(4, 4, 2)),
        ],
    )
    cfg = new_config(
        Config(
            physical_cluster=PhysicalClusterSpec(
                cell_types={"slice64": CellTypeSpec(mesh=mesh)},
                physical_cells=[PhysicalCellSpec(cell_type="slice64", cell_address="n0")],
            ),
            virtual_clusters={"vc": VirtualClusterSpec()},
        )
    )
    parsed = parse_config(cfg)
    full = parsed.physical_full_list["slice64"]
    node = full[max(full)][0]
    levels = {lv.level: lv.leaf_cell_number for lv in parsed.chain_levels["slice64"]}
    return node, levels


def _forced(node, avail, num, levels, threshold, direct):
    import os
    saved_threshold = ta._NATIVE_THRESHOLD
    saved_env = os.environ.get("HIVED_DIRECT")
    ta._NATIVE_THRESHOLD = threshold
    os.environ["HIVED_DIRECT"] = "1" if direct else "0"
    try:
        return ta.find_leaf_cells_in_node(node, num, 0, list(avail), levels)
    finally:
        ta._NATIVE_THRESHOLD = saved_threshold
        if saved_env is None:
            os.environ.pop("HIVED_DIRECT", None)
        else:
            os.environ["HIVED_DIRECT"] = saved_env


def _py(node, avail, num, levels):
    # force the legacy Python backtracking branch
    return _forced(node, avail, num, levels, threshold=10**9, direct=False)


def native_search(node, avail, num, levels):
    return _forced(node, avail, num, levels, threshold=0, direct=False)


def direct_search(node, avail, num, levels):
    # the round-3 path: direct aligned-enclosure enumeration (forced on
    # regardless of candidate count)
    return _forced(node, avail, num, levels, threshold=0, direct=True)


@pytest.mark.parametrize("num", [1, 2, 4, 8, 16])
def test_differential_full_node(num):
    node, levels = big_node()
    leaves = []

    def collect(c):
        if c.level == 1:
            leaves.append(c)
        else:
            for cc in c.children:
                collect(cc)

    collect(node)
    py_picked, _ = _py(node, leaves, num, levels)
    nat_picked, _ = native_search(node, leaves, num, levels)
    assert [c.address for c in py_picked] == [c.address for c in nat_picked]


@pytest.mark.parametrize("seed", range(8))
def test_differential_fragmented(seed):
    """Random subsets of free chips (fragmentation) at random request sizes."""
    rng = random.Random(seed)
    node, levels = big_node()
    leaves = []

    def collect(c):
        if c.level == 1:
            leaves.append(c)
        else:
            for cc in c.children:
                collect(cc)

    collect(node)
    avail = [c for c in leaves if rng.random() < 0.6]
    num = rng.choice([1, 2, 3, 4, 5, 8])
    if len(avail) < num:
        return
    py_picked, py_rest = _py(node, avail, num, levels)
    nat_picked, nat_rest = native_search(node, avail, num, levels)
    assert [c.address for c in py_picked] == [c.address for c in nat_picked]
    assert [c.address for c in py_rest] == [c.address for c in nat_rest]


def test_native_speedup_adversarial_fragmentation():
    """Worst case for the backtracking search: one chip removed from every
    8-chip sub-cube, so an 8-chip request can never reach level-3 affinity and
    the search must prove the best is level 4. The C++ path must win big
    (typically ~80x) and pick identical cells."""
    import time

    node, levels = big_node()
    leaves = []

    def collect(c):
        if c.level == 1:
            leaves.append(c)
        else:
            for cc in c.children:
                collect(cc)

    collect(node)
    blocks = {}
    for leaf in leaves:
        key = tuple(o // 2 for o in leaf.mesh_origin)
        blocks.setdefault(key, []).append(leaf)
    avail = []
    for blk in blocks.values():
        avail.extend(blk[1:])  # drop one chip per 8-block

    t0 = time.perf_counter()
    py_picked, _ = _py(node, avail, 8, levels)
    t_py = time.perf_counter() - t0
    t0 = time.perf_counter()
    nat_picked, _ = native_search(node, avail, 8, levels)
    t_nat = time.perf_counter() - t0
    assert [c.address for c in py_picked] == [c.address for c in nat_picked]
    assert t_nat < t_py / 5, (t_nat, t_py)


def _collect_leaves(node):
    leaves = []

    def collect(c):
        if c.level == 1:
            leaves.append(c)
        else:
            for cc in c.children:
                collect(cc)

    collect(node)
    return leaves


@pytest.mark.parametrize("seed", range(10))
def test_differential_direct_vs_backtracking(seed):
    """Round-3 mesh-direct search: the direct aligned-enclosure enumeration
    must pick exactly the same cells (and leave the same remainder) as the
    reference backtracking search, across random fragmentation patterns and
    request sizes."""
    rng = random.Random(1000 + seed)
    node, levels = big_node()
    leaves = _collect_leaves(node)
    avail = [c for c in leaves if rng.random() < rng.choice([0.3, 0.6, 0.9])]
    # larger requests explode the backtracking REFERENCE (the very cost the
    # direct path removes); keep CI affordable and cover size via the
    # adversarial test below
    num = rng.choice([1, 2, 3, 4, 5, 6, 8])
    if len(avail) < num:
        return
    py_picked, py_rest = _py(node, avail, num, levels)
    d_picked, d_rest = direct_search(node, avail, num, levels)
    assert [c.address for c in py_picked] == [c.address for c in d_picked]
    assert [c.address for c in py_rest] == [c.address for c in d_rest]


def test_direct_beats_backtracking_adversarial():
    """The direct enumeration is near-linear: on the same adversarial
    fragmentation that makes the backtracking search prove optimality by
    exhaustion, it must beat the pure-Python backtracking by >100x while
    picking identical cells (it replaces even the C++ accelerated path on
    the hot path)."""
    import time

    node, levels = big_node()
    leaves = _collect_leaves(node)
    blocks = {}
    for leaf in leaves:
        key = tuple(o // 2 for o in leaf.mesh_origin)
        blocks.setdefault(key, []).append(leaf)
    avail = []
    for blk in blocks.values():
        avail.extend(blk[1:])  # drop one chip per 8-block

    t0 = time.perf_counter()
    py_picked, _ = _py(node, avail, 8, levels)
    t_py = time.perf_counter() - t0
    t0 = time.perf_counter()
    d_picked, _ = direct_search(node, avail, 8, levels)
    t_direct = time.perf_counter() - t0
    assert [c.address for c in py_picked] == [c.address for c in d_picked]
    assert t_direct < t_py / 100, (t_direct, t_py)


# ---------------------------------------------------------------------------
# cross-node packing: hived_find_nodes_for_pods parity (perf PR)
# ---------------------------------------------------------------------------


def _packing_cluster():
    """A multi-node cluster view: one 256-chip pod of 64 4-chip hosts."""
    mesh = MeshSpec(
        topology=(8, 8, 4),
        chip_type="chip",
        host_shape=(2, 2, 1),
        levels=[
            MeshLevelSpec(name="m8", shape=(2, 2, 2)),
            MeshLevelSpec(name="m16", shape=(4, 2, 2)),
            MeshLevelSpec(name="m32", shape=(4, 4, 2)),
            MeshLevelSpec(name="m64", shape=(4, 4, 4)),
            MeshLevelSpec(name="m128", shape=(8, 4, 4)),
        ],
    )
    cfg = new_config(
        Config(
            physical_cluster=PhysicalClusterSpec(
                cell_types={"pod256": CellTypeSpec(mesh=mesh)},
                physical_cells=[
                    PhysicalCellSpec(cell_type="pod256", cell_address="p0")
                ],
            ),
            virtual_clusters={"vc": VirtualClusterSpec()},
        )
    )
    parsed = parse_config(cfg)
    ccl = parsed.physical_full_list["pod256"]
    levels = {lv.level: lv.leaf_cell_number
              for lv in parsed.chain_levels["pod256"]}
    return ccl, levels


@pytest.mark.parametrize("seed", range(6))
def test_packing_native_vs_python_parity(seed):
    """HIVED_NATIVE=0 vs native parity for the cross-node packing entry
    point: two schedulers over the SAME cells — one using the one-call C
    packing (sort + enclosure pass + greedy), one forced onto the Python
    incremental path — must pick IDENTICAL nodes and produce byte-identical
    failure reasons across randomized load, health and suggested-node
    churn. Both maintain their own persistent sort order from the same
    seed, so strict equality (not just score equality) is the contract."""
    import random as _random

    from hivedscheduler_tpu.algorithm.cell_allocation import (
        allocate_cell_walk,
        release_cell_walk,
    )
    from hivedscheduler_tpu.algorithm import topology_aware as ta

    if not native.pack_available():
        pytest.skip("native packing entry unavailable")
    rng = _random.Random(seed)
    ccl, levels = _packing_cluster()
    s_nat = ta.TopologyAwareScheduler(ccl, levels, cross_priority_pack=False)
    s_py = ta.TopologyAwareScheduler(ccl, levels, cross_priority_pack=False)
    s_py._native_pack = False  # force the Python incremental reference
    assert s_nat._native_pack_state() is not None, "native packing not engaged"

    leaves = ccl[1]
    all_nodes = sorted({c.nodes[0] for c in leaves})
    allocated = []
    for step in range(40):
        # churn: allocate or release random leaves at random priorities
        if allocated and rng.random() < 0.45:
            for _ in range(rng.randint(1, 8)):
                if not allocated:
                    break
                c, p = allocated.pop(rng.randrange(len(allocated)))
                release_cell_walk(c, p)
        else:
            for _ in range(rng.randint(1, 8)):
                c = leaves[rng.randrange(len(leaves))]
                p = rng.choice([-1, 0, 5])
                allocate_cell_walk(c, p)
                allocated.append((c, p))
        # health churn
        if rng.random() < 0.3:
            c = leaves[rng.randrange(len(leaves))]
            c.set_healthiness("Bad" if c.healthy else "Healthy")
        ignore = rng.random() < 0.5
        if ignore:
            suggested = set()
        else:
            suggested = set(rng.sample(all_nodes,
                                       rng.randint(0, len(all_nodes))))
        nums = rng.choice([[4], [4, 4], [4] * 8, [8] * 4, [4] * 64,
                           [16] * 2, [4] * 63 + [8]])
        p = rng.choice([-1, 5])
        for s in (s_nat, s_py):
            s._update_cluster_view(p, suggested, ignore)
        picked_nat, reason_nat = s_nat._find_nodes(sorted(nums), True)
        picked_py, reason_py = s_py._find_nodes(sorted(nums), True)
        assert picked_nat == picked_py, (step, nums, picked_nat, picked_py)
        assert reason_nat == reason_py, (step, nums, reason_nat, reason_py)
        assert s_nat._order == s_py._order, step
