"""Multi-chain relaxation: one affinity group spanning several cell chains of
the same leaf type when no single chain fits it.

Closes the reference's TODO (``intra_vc_scheduler.go:52``) — the reference
can only wait in this situation. VC safety must hold per chain, the gang
stays all-or-nothing, and recovery must survive per-pod chains.
"""

import logging
import random

import pytest

from helpers import make_pod

from hivedscheduler_tpu.api.config import Config, new_config
from hivedscheduler_tpu.api.types import (
    CellTypeSpec,
    MeshLevelSpec,
    MeshSpec,
    PhysicalCellSpec,
    PhysicalClusterSpec,
    VirtualCellSpec,
    VirtualClusterSpec,
)
from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.algorithm.constants import GROUP_ALLOCATED
from hivedscheduler_tpu.k8s.types import Node
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

logging.getLogger().setLevel(logging.ERROR)


def build_config():
    """Two v5p chains of 8 chips each (2x2x2 mesh, 4-chip hosts); vc1 owns
    both whole chains, vc2 owns nothing here."""
    def mesh():
        return MeshSpec(
            topology=(2, 2, 2), chip_type="v5p-chip", host_shape=(2, 2, 1),
            levels=[MeshLevelSpec(name_shape[0], name_shape[1])
                    for name_shape in []],
        )

    return new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={
                "podA": CellTypeSpec(mesh=mesh()),
                "podB": CellTypeSpec(mesh=mesh()),
            },
            physical_cells=[
                PhysicalCellSpec(cell_type="podA", cell_address="a0"),
                PhysicalCellSpec(cell_type="podB", cell_address="b0"),
            ],
        ),
        virtual_clusters={
            "vc1": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=1, cell_type="podA"),
                VirtualCellSpec(cell_number=1, cell_type="podB"),
            ]),
        },
    ))


@pytest.fixture
def algo():
    random.seed(0)
    h = HivedAlgorithm(build_config())
    for n in sorted({n for ccl in h.full_cell_list.values()
                     for c in ccl[max(ccl)] for n in c.nodes}):
        h.add_node(Node(name=n))
    return h


def nodes_of(h):
    return sorted({n for ccl in h.full_cell_list.values()
                   for c in ccl[max(ccl)] for n in c.nodes})


def gang_spec(pods, name="relax", prio=1):
    return {"virtualCluster": "vc1", "priority": prio, "chipType": "v5p-chip",
            "chipNumber": 4,
            "affinityGroup": {"name": name,
                              "members": [{"podNumber": pods, "chipNumber": 4}]}}


def free_snapshot(h):
    return {
        (chain, lv): sorted(c.address for c in ccl[lv])
        for chain, ccl in h.free_cell_list.items()
        for lv in sorted(ccl)
    }


def build_three_chain_config():
    """Two 8-chip chains (config-listed FIRST) + one 16-chip chain, all owned
    whole by vc1 — the asymmetric fixture for the capacity-first partition
    tests."""
    small = MeshSpec(topology=(2, 2, 2), chip_type="v5p-chip",
                     host_shape=(2, 2, 1), levels=[])
    big = MeshSpec(topology=(4, 2, 2), chip_type="v5p-chip",
                   host_shape=(2, 2, 1), levels=[])
    return new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={
                "podA": CellTypeSpec(mesh=small),
                "podB": CellTypeSpec(mesh=small),
                "podC": CellTypeSpec(mesh=big),
            },
            physical_cells=[
                PhysicalCellSpec(cell_type="podA", cell_address="a0"),
                PhysicalCellSpec(cell_type="podB", cell_address="b0"),
                PhysicalCellSpec(cell_type="podC", cell_address="c0"),
            ],
        ),
        virtual_clusters={
            "vc1": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=1, cell_type="podA"),
                VirtualCellSpec(cell_number=1, cell_type="podB"),
                VirtualCellSpec(cell_number=1, cell_type="podC"),
            ]),
        },
    ))


class TestMultiChainRelaxation:
    def test_group_spans_two_chains(self, algo):
        """3 pods x 4 chips = 12 chips; each chain holds 8. Only a relaxed
        placement fits — and it must be a real gang (all three bind)."""
        nodes = nodes_of(algo)
        initial = free_snapshot(algo)
        spec = gang_spec(3)
        bound, chains_used = [], set()
        for i in range(3):
            pod = make_pod(f"r-{i}", spec)
            r = algo.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, (i, r.pod_wait_info)
            chains_used.add(r.pod_bind_info.cell_chain)
            bp = new_binding_pod(pod, r.pod_bind_info)
            algo.add_allocated_pod(bp)
            bound.append(bp)
        assert chains_used == {"podA", "podB"}, (
            f"gang must span both chains, used {chains_used}"
        )
        g = algo.get_affinity_group("relax")
        assert g.status.state == GROUP_ALLOCATED
        # full delete restores both chains' free lists exactly
        for bp in reversed(bound):
            algo.delete_allocated_pod(bp)
        assert free_snapshot(algo) == initial

    def test_single_chain_still_preferred(self, algo):
        """A gang that fits one chain must NOT be relaxed."""
        nodes = nodes_of(algo)
        spec = gang_spec(2, name="fits")
        chains_used = set()
        for i in range(2):
            pod = make_pod(f"f-{i}", spec)
            r = algo.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None
            chains_used.add(r.pod_bind_info.cell_chain)
            algo.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
        assert len(chains_used) == 1

    def test_relaxation_is_all_or_nothing(self, algo):
        """5 pods x 4 chips = 20 chips > 16 total: must wait, and the failed
        relaxation must leave no state behind."""
        nodes = nodes_of(algo)
        initial = free_snapshot(algo)
        r = algo.schedule(make_pod("w-0", gang_spec(5, name="toolarge")),
                          nodes, FILTERING_PHASE)
        assert r.pod_wait_info is not None
        assert free_snapshot(algo) == initial
        assert "toolarge" not in {g.name for g in algo.get_all_affinity_groups()}

    def test_relaxed_group_recovers_through_crash(self, algo):
        """Replay the multi-chain gang's bind annotations into a fresh
        scheduler: per-pod chains + cross-chain fallback must reconstruct the
        same placement."""
        nodes = nodes_of(algo)
        spec = gang_spec(3, name="recover")
        bound = []
        for i in range(3):
            pod = make_pod(f"c-{i}", spec)
            r = algo.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None
            bp = new_binding_pod(pod, r.pod_bind_info)
            algo.add_allocated_pod(bp)
            bound.append(bp)
        placement = {
            bp.uid: sorted(algo.get_affinity_group("recover").status
                           .physical_placement)
            for bp in bound
        }
        fresh = HivedAlgorithm(build_config())
        for n in nodes:
            fresh.add_node(Node(name=n))
        for bp in bound:
            fresh.add_allocated_pod(bp)
        g = fresh.get_affinity_group("recover")
        assert g.status.state == GROUP_ALLOCATED
        assert sorted(g.status.physical_placement) == sorted(
            algo.get_affinity_group("recover").status.physical_placement
        )

    def test_opt_out_restores_reference_wait_behavior(self, algo):
        """multiChainRelaxEnable: false — the gang must wait exactly like the
        reference instead of being split across chains."""
        nodes = nodes_of(algo)
        spec = gang_spec(3, name="nosplit")
        spec["multiChainRelaxEnable"] = False
        r = algo.schedule(make_pod("n-0", spec), nodes, FILTERING_PHASE)
        assert r.pod_wait_info is not None, r.pod_bind_info

    def test_partition_touches_fewest_chains(self):
        """Capacity-first partition: with chains of 8, 8 and 16 chips (small
        ones FIRST in config order), a 24-chip gang must land on 2 chains —
        the 16-chip chain hosting 4 pods — not be smeared across all 3 in
        config order."""
        random.seed(0)
        h = HivedAlgorithm(build_three_chain_config())
        nodes = nodes_of(h)
        for n in nodes:
            h.add_node(Node(name=n))
        spec = gang_spec(6, name="fewest")
        per_chain = {}
        for i in range(6):
            pod = make_pod(f"p-{i}", spec)
            r = h.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, (i, r.pod_wait_info)
            per_chain[r.pod_bind_info.cell_chain] = (
                per_chain.get(r.pod_bind_info.cell_chain, 0) + 1
            )
            h.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
        assert per_chain.get("podC") == 4, per_chain
        assert len(per_chain) == 2, per_chain

    def test_partition_counts_preemptible_capacity(self):
        """The capacity ranking must count lazily-preemptible lower-priority
        usage, not just free cells: with the 16-chip chain fully held by a
        priority-1 gang, a priority-10 24-chip gang must still take 4 pods
        there (evicting the victims) + 2 on one 8-chip chain = 2 chains, not
        smear across all 3 because the big chain has zero *free* cells."""
        random.seed(0)
        h = HivedAlgorithm(build_three_chain_config())
        nodes = nodes_of(h)
        for n in nodes:
            h.add_node(Node(name=n))
        from hivedscheduler_tpu.runtime.types import PREEMPTING_PHASE

        # a 16-chip priority-1 gang lands whole on podC (the only chain that
        # fits it single-chain)
        low_spec = gang_spec(4, name="low", prio=1)
        bound = {}
        for i in range(4):
            pod = make_pod(f"low-{i}", low_spec)
            r = h.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None
            assert r.pod_bind_info.cell_chain == "podC"
            bp = new_binding_pod(pod, r.pod_bind_info)
            h.add_allocated_pod(bp)
            bound[bp.uid] = bp

        hi_spec = gang_spec(6, name="high", prio=10)
        per_chain = {}
        for i in range(6):
            pod = make_pod(f"hi-{i}", hi_spec)
            r = None
            for attempt in range(32):
                r = h.schedule(
                    pod, nodes,
                    PREEMPTING_PHASE if attempt else FILTERING_PHASE,
                )
                if r.pod_preempt_info is not None:
                    for victim in r.pod_preempt_info.victim_pods:
                        bp = bound.pop(victim.uid, None)
                        if bp is not None:
                            h.delete_allocated_pod(bp)
                    continue
                break
            assert r.pod_bind_info is not None, (i, r.pod_wait_info)
            per_chain[r.pod_bind_info.cell_chain] = (
                per_chain.get(r.pod_bind_info.cell_chain, 0) + 1
            )
            h.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
        assert per_chain.get("podC") == 4, per_chain
        assert len(per_chain) == 2, per_chain

    def test_any_type_prefers_whole_gang_on_other_type_over_splitting(self):
        """An untyped gang that no single chain of type A fits must NOT be
        split across A's chains when a single chain of type B can host it
        whole — all single-chain attempts across all types run before any
        relaxation."""
        random.seed(0)
        mesh_a = MeshSpec(topology=(2, 2, 2), chip_type="a-chip",
                          host_shape=(2, 2, 1), levels=[])
        mesh_b = MeshSpec(topology=(4, 2, 2), chip_type="b-chip",
                          host_shape=(2, 2, 1), levels=[])
        cfg = new_config(Config(
            physical_cluster=PhysicalClusterSpec(
                cell_types={
                    "aA": CellTypeSpec(mesh=mesh_a),
                    "aB": CellTypeSpec(mesh=mesh_a),
                    "bigB": CellTypeSpec(mesh=mesh_b),
                },
                physical_cells=[
                    PhysicalCellSpec(cell_type="aA", cell_address="aa0"),
                    PhysicalCellSpec(cell_type="aB", cell_address="ab0"),
                    PhysicalCellSpec(cell_type="bigB", cell_address="bb0"),
                ],
            ),
            virtual_clusters={
                "vc1": VirtualClusterSpec(virtual_cells=[
                    VirtualCellSpec(cell_number=1, cell_type="aA"),
                    VirtualCellSpec(cell_number=1, cell_type="aB"),
                    VirtualCellSpec(cell_number=1, cell_type="bigB"),
                ]),
            },
        ))
        h = HivedAlgorithm(cfg)
        nodes = sorted({n for ccl in h.full_cell_list.values()
                        for c in ccl[max(ccl)] for n in c.nodes})
        for n in nodes:
            h.add_node(Node(name=n))
        spec = {"virtualCluster": "vc1", "priority": 1, "chipNumber": 4,
                "affinityGroup": {"name": "untyped",
                                  "members": [{"podNumber": 3, "chipNumber": 4}]}}
        chains_used = set()
        for i in range(3):
            pod = make_pod(f"u-{i}", spec)
            r = h.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, (i, r.pod_wait_info)
            chains_used.add(r.pod_bind_info.cell_chain)
            h.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
        assert chains_used == {"bigB"}, (
            f"whole-gang placement on bigB must beat splitting across aA/aB; "
            f"got {chains_used}"
        )

    def test_opportunistic_gang_relaxes_too(self, algo):
        nodes = nodes_of(algo)
        spec = gang_spec(4, name="opp", prio=-1)
        chains_used = set()
        for i in range(4):
            pod = make_pod(f"o-{i}", spec)
            r = algo.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, (i, r.pod_wait_info)
            chains_used.add(r.pod_bind_info.cell_chain)
            algo.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
        assert chains_used == {"podA", "podB"}


def build_two_big_chain_config():
    """Two 16-chip chains wholly owned by vc1 — the balanced-vs-fewest
    partition fixture (a 24-chip gang fits on neither alone)."""
    big = MeshSpec(topology=(4, 2, 2), chip_type="v5p-chip",
                   host_shape=(2, 2, 1), levels=[])
    return new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={
                "podA": CellTypeSpec(mesh=big),
                "podB": CellTypeSpec(mesh=big),
            },
            physical_cells=[
                PhysicalCellSpec(cell_type="podA", cell_address="a0"),
                PhysicalCellSpec(cell_type="podB", cell_address="b0"),
            ],
        ),
        virtual_clusters={
            "vc1": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=1, cell_type="podA"),
                VirtualCellSpec(cell_number=1, cell_type="podB"),
            ]),
        },
    ))


class TestBalancedRelaxPolicy:
    """multiChainRelaxPolicy: balanced — equalize sub-gang chip counts
    over the minimal chain set (per-sub-gang ICI collective phases pace
    evenly) instead of largest-prefix-first (one oversized sub-gang
    straggles the hierarchical collective)."""

    def run_gang(self, policy, name):
        return self.run_gang_pods(6, policy, name)[0]

    def test_balanced_beats_fewest_on_max_subgang(self):
        """The golden delta on the adversarial fixture: same 2 chains,
        fewest-chains takes 16+8 chips (max sub-gang 16 — its ICI phase
        paces the whole collective), balanced takes 12+12."""
        fewest = self.run_gang(None, "fw")
        balanced = self.run_gang("balanced", "bl")
        assert sorted(fewest.values()) == [2, 4], fewest
        assert sorted(balanced.values()) == [3, 3], balanced
        assert max(balanced.values()) < max(fewest.values())
        assert len(balanced) == len(fewest) == 2  # same chain count

    def test_balanced_feasibility_never_regresses(self):
        """A gang that doesn't split evenly (5 pods over two 16-chip
        chains) must still fully place under balanced — the shortfall on
        the first chain rolls forward into the next chain's allowance —
        and every pod must hold DISJOINT physical chips (round 5 review
        caught a fallback re-probe double-booking the same leaf cells;
        this pins the fix)."""
        per_chain, placements = self.run_gang_pods(5, "balanced", "odd")
        assert sum(per_chain.values()) == 5
        assert len(placements) == len(set(placements)) == 5
        chips_used = set()
        for node, iso in placements:
            for chip in iso.split(","):
                assert (node, chip) not in chips_used, (node, chip)
                chips_used.add((node, chip))

    def run_gang_pods(self, pods, policy, name, algo=None):
        from hivedscheduler_tpu.api import constants as C

        random.seed(0)
        h = algo or HivedAlgorithm(build_two_big_chain_config())
        nodes = sorted({n for ccl in h.full_cell_list.values()
                        for c in ccl[max(ccl)] for n in c.nodes})
        for n in nodes:
            h.add_node(Node(name=n))
        spec = gang_spec(pods, name=name)
        if policy:
            spec["multiChainRelaxPolicy"] = policy
        per_chain = {}
        placements = []
        for i in range(pods):
            pod = make_pod(f"{name}-{i}", spec)
            r = h.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, (i, r.pod_wait_info)
            per_chain[r.pod_bind_info.cell_chain] = (
                per_chain.get(r.pod_bind_info.cell_chain, 0) + 1
            )
            bp = new_binding_pod(pod, r.pod_bind_info)
            placements.append((r.pod_bind_info.node,
                               bp.annotations[C.ANNOTATION_POD_CHIP_ISOLATION]))
            h.add_allocated_pod(bp)
        return per_chain, placements

    def test_balanced_falls_back_when_caps_overestimate(self):
        """root_available is an optimistic estimate: chain B's 14
        available chips hide that only two clean 4-cells (8 chips) are
        achievable once higher-priority chips sit scattered across its
        hosts. The balanced targets (12+12) then come up short, and the
        policy must rerun under fewest allowances (16+8) instead of
        leaving the gang waiting — with all placements disjoint."""
        random.seed(0)
        h = HivedAlgorithm(build_two_big_chain_config())
        nodes = sorted({n for ccl in h.full_cell_list.values()
                        for c in ccl[max(ccl)] for n in c.nodes})
        for n in nodes:
            h.add_node(Node(name=n))
        # two priority-10 single-chip blockers on DIFFERENT hosts of podB
        blocker = {"virtualCluster": "vc1", "priority": 10,
                   "chipType": "v5p-chip", "chipNumber": 1,
                   "ignoreK8sSuggestedNodes": False,
                   "affinityGroup": None}
        placed_hosts = set()
        for i in range(2):
            spec = dict(blocker)
            spec["affinityGroup"] = {
                "name": f"blk-{i}",
                "members": [{"podNumber": 1, "chipNumber": 1}]}
            pod = make_pod(f"blk-{i}", spec)
            b_nodes = [n for n in nodes if n.startswith("b0")
                       and n not in placed_hosts]
            r = h.schedule(pod, b_nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, r.pod_wait_info
            placed_hosts.add(r.pod_bind_info.node)
            h.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
        assert len(placed_hosts) == 2  # scattered: two hosts each lose a chip

        per_chain, placements = self.run_gang_pods(6, "balanced", "cap",
                                                   algo=h)
        assert sum(per_chain.values()) == 6
        assert per_chain == {"podA": 4, "podB": 2}, per_chain
        chips_used = set()
        for node, iso in placements:
            for chip in iso.split(","):
                assert (node, chip) not in chips_used, (node, chip)
                chips_used.add((node, chip))

    def test_unknown_policy_rejected(self):
        random.seed(0)
        h = HivedAlgorithm(build_two_big_chain_config())
        nodes = sorted({n for ccl in h.full_cell_list.values()
                        for c in ccl[max(ccl)] for n in c.nodes})
        for n in nodes:
            h.add_node(Node(name=n))
        spec = gang_spec(2, name="bad")
        spec["multiChainRelaxPolicy"] = "balenced"
        import pytest as _pytest

        from hivedscheduler_tpu.api.types import WebServerError

        with _pytest.raises(WebServerError,
                            match="MultiChainRelaxPolicy"):
            h.schedule(make_pod("bad-0", spec), nodes, FILTERING_PHASE)


def test_balanced_three_chain_water_fill():
    """Water-fill over three heterogeneous chains: 36 chips across caps
    16/16/8 needs k=3; the smallest cap pins first (8), the remainder
    splits 14/14 over the big chains (pod granularity: 3/4 + 3/4 + 2).
    Fewest-chains greedy would take 16+16+4 (4/4/1)."""
    random.seed(0)
    big = MeshSpec(topology=(4, 2, 2), chip_type="v5p-chip",
                   host_shape=(2, 2, 1), levels=[])
    small = MeshSpec(topology=(2, 2, 2), chip_type="v5p-chip",
                     host_shape=(2, 2, 1), levels=[])
    cfg = new_config(Config(
        physical_cluster=PhysicalClusterSpec(
            cell_types={
                "podA": CellTypeSpec(mesh=big),
                "podB": CellTypeSpec(mesh=big),
                "podC": CellTypeSpec(mesh=small),
            },
            physical_cells=[
                PhysicalCellSpec(cell_type="podA", cell_address="a0"),
                PhysicalCellSpec(cell_type="podB", cell_address="b0"),
                PhysicalCellSpec(cell_type="podC", cell_address="c0"),
            ],
        ),
        virtual_clusters={
            "vc1": VirtualClusterSpec(virtual_cells=[
                VirtualCellSpec(cell_number=1, cell_type="podA"),
                VirtualCellSpec(cell_number=1, cell_type="podB"),
                VirtualCellSpec(cell_number=1, cell_type="podC"),
            ]),
        },
    ))

    def run(policy):
        random.seed(0)
        h = HivedAlgorithm(cfg)
        nodes = sorted({n for ccl in h.full_cell_list.values()
                        for c in ccl[max(ccl)] for n in c.nodes})
        for n in nodes:
            h.add_node(Node(name=n))
        spec = gang_spec(9, name=f"tri-{policy}")
        if policy:
            spec["multiChainRelaxPolicy"] = policy
        per_chain = {}
        for i in range(9):
            pod = make_pod(f"tri-{policy}-{i}", spec)
            r = h.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None, (i, r.pod_wait_info)
            per_chain[r.pod_bind_info.cell_chain] = (
                per_chain.get(r.pod_bind_info.cell_chain, 0) + 1
            )
            h.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
        return per_chain

    fewest = run(None)
    balanced = run("balanced")
    assert sorted(fewest.values()) == [1, 4, 4], fewest
    assert sorted(balanced.values()) == [2, 3, 4], balanced
    assert max(balanced.values()) <= max(fewest.values())
    assert min(balanced.values()) > min(fewest.values())  # no lonely pod
