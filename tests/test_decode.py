"""KV-cache decoding tests: incremental logits must equal the full forward
(teacher forcing), the GQA cache must stay compact, MoE models must decode,
and generate() must be deterministic under greedy decoding."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import decode, transformer as tm  # noqa: E402


def cfg_of(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq_len=32, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


def setup(cfg, b=2, t=12, seed=0):
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        params = tm.init_params(cfg, jax.random.PRNGKey(seed))
        tokens = jax.random.randint(
            jax.random.PRNGKey(seed + 1), (b, t), 0, cfg.vocab_size
        )
    return params, tokens


class TestKVCacheDecode:
    # n_kv=1 (MQA) is slow-marked: tier-1 wall-time budget (ISSUE 13) —
    # n_kv=0 (MHA) and n_kv=2 (GQA) are the tier-1 cousins through the
    # same grouped-attention read path
    @pytest.mark.parametrize(
        "n_kv", [0, 2, pytest.param(1, marks=pytest.mark.slow)])
    def test_incremental_matches_full_forward(self, n_kv):
        """Prefill 6 tokens then decode the rest one at a time: every
        incremental logit row must equal the full forward's row."""
        cfg = cfg_of(n_kv_heads=n_kv)
        params, tokens = setup(cfg)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            full = tm.forward(params, tokens, cfg)
        cache = decode.init_kv_cache(cfg, tokens.shape[0], tokens.shape[1])
        logits_pre, cache = decode.advance(params, cache, tokens[:, :6], cfg)
        np.testing.assert_allclose(
            np.asarray(logits_pre), np.asarray(full[:, :6]), atol=2e-5
        )
        for i in range(6, tokens.shape[1]):
            step_logits, cache = decode.advance(
                params, cache, tokens[:, i:i + 1], cfg
            )
            np.testing.assert_allclose(
                np.asarray(step_logits[:, 0]), np.asarray(full[:, i]),
                atol=2e-5, err_msg=f"position {i}",
            )

    def test_gqa_cache_is_compact(self):
        cfg = cfg_of(n_kv_heads=1)
        cache = decode.init_kv_cache(cfg, batch=2, max_len=16)
        assert cache.k.shape == (2, 2, 16, 1, cfg.head_dim)

    def test_moe_model_decodes(self):
        cfg = cfg_of(n_experts=4, expert_capacity_factor=8.0)
        params, tokens = setup(cfg)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            full = tm.forward(params, tokens, cfg)
        cache = decode.init_kv_cache(cfg, tokens.shape[0], tokens.shape[1])
        logits, cache = decode.advance(params, cache, tokens[:, :-1], cfg)
        # ample capacity: the MoE decode path must match the full forward
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, :-1]), atol=2e-5
        )
        step_logits, _ = decode.advance(params, cache, tokens[:, -1:], cfg)
        assert np.isfinite(np.asarray(step_logits)).all()

    def test_moe_decode_uses_no_drop_capacity(self):
        """With a TIGHT training capacity factor, decode must still deliver
        every token to its experts: its logits equal a no-drop training
        forward (capacity factor = E), not the dropping one."""
        import dataclasses

        cfg = cfg_of(n_experts=4, expert_capacity_factor=1.0)
        params, tokens = setup(cfg)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            nodrop = tm.forward(
                params, tokens,
                dataclasses.replace(cfg, expert_capacity_factor=4.0),
            )
            dropping = tm.forward(params, tokens, cfg)
        cache = decode.init_kv_cache(cfg, tokens.shape[0], tokens.shape[1])
        logits, _ = decode.advance(params, cache, tokens, cfg)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(nodrop), atol=2e-5
        )
        # sanity: the tight factor actually dropped something, so the two
        # references differ and this test discriminates
        assert np.abs(np.asarray(nodrop) - np.asarray(dropping)).max() > 1e-3

    def test_greedy_generate_is_deterministic_and_consistent(self):
        """generate() must agree with manual argmax teacher-forced rollout."""
        cfg = cfg_of()
        params, prompt = setup(cfg, t=5)
        out1 = decode.generate(params, prompt, cfg, max_new_tokens=6)
        out2 = decode.generate(params, prompt, cfg, max_new_tokens=6)
        assert out1.shape == (2, 6)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
        # manual rollout via repeated full forwards
        seq = prompt
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            for _ in range(6):
                logits = tm.forward(params, seq, cfg)
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(seq.dtype)
                seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(
            np.asarray(out1), np.asarray(seq[:, 5:])
        )

    def test_tp_sharded_generate_matches_single_device(self):
        """Tensor-parallel serving: greedy tokens from a dp=2 x tp=2 mesh
        must equal the single-device decode exactly."""
        from hivedscheduler_tpu.parallel import topology

        cfg = cfg_of(n_kv_heads=2)
        params, prompt = setup(cfg, t=5)
        ref = decode.generate(params, prompt, cfg, max_new_tokens=6)
        axes = topology.MeshAxes(dp=2, tp=2)
        mesh = topology.make_mesh(axes, topology.get_devices(axes.size))
        run, param_sh, prompt_sh = decode.make_sharded_generate(
            cfg, mesh, max_new_tokens=6
        )
        sharded_params = jax.device_put(params, param_sh)
        sharded_prompt = jax.device_put(prompt, prompt_sh)
        out = run(sharded_params, sharded_prompt)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_sharded_generate_rejects_indivisible_heads(self):
        from hivedscheduler_tpu.parallel import topology

        cfg = cfg_of(n_kv_heads=1)
        axes = topology.MeshAxes(tp=2)
        mesh = topology.make_mesh(axes, topology.get_devices(axes.size))
        with pytest.raises(ValueError, match="divide the tp axis"):
            decode.make_sharded_generate(cfg, mesh, max_new_tokens=4)

    def test_sampled_generate_runs(self):
        cfg = cfg_of()
        params, prompt = setup(cfg, t=4)
        out = decode.generate(
            params, prompt, cfg, max_new_tokens=5, temperature=0.8,
            key=jax.random.PRNGKey(3),
        )
        assert out.shape == (2, 5)
        assert ((np.asarray(out) >= 0) & (np.asarray(out) < 64)).all()


class TestSamplingFilters:
    """top-k / top-p (nucleus) logit filtering, decode.filter_logits."""

    def test_top_k_masks_all_but_k(self):
        logits = jnp.array([[1.0, 3.0, 2.0, -1.0, 0.5]])
        out = decode.filter_logits(logits, top_k=2)
        kept = np.asarray(out[0] > -1e29)
        assert kept.tolist() == [False, True, True, False, False]

    def test_top_p_keeps_smallest_covering_prefix(self):
        # probs ~ [0.643, 0.237, 0.087, 0.032] -> top_p=0.7 keeps the first
        # two (prefix crosses 0.7 at the second token)
        logits = jnp.log(jnp.array([[0.643, 0.237, 0.087, 0.033]]))
        out = decode.filter_logits(logits, top_p=0.7)
        kept = np.asarray(out[0] > -1e29)
        assert kept.tolist() == [True, True, False, False]

    def test_top_p_one_and_top_k_zero_are_identity(self):
        logits = jnp.array([[1.0, 3.0, 2.0]])
        out = decode.filter_logits(logits, top_k=0, top_p=1.0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(logits))

    def test_sampled_generation_respects_top_k_one(self):
        # top_k=1 sampling must equal greedy decoding whatever the seed
        cfg = cfg_of()
        params, prompt = setup(cfg)
        greedy = decode.generate(params, prompt, cfg, 6)
        sampled = decode.generate(
            params, prompt, cfg, 6, temperature=1.3, top_k=1,
            key=jax.random.PRNGKey(9),
        )
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(sampled))

    def test_filters_compose_k_then_p(self):
        logits = jnp.log(jnp.array([[0.40, 0.30, 0.15, 0.10, 0.05]]))
        # top_k=3 keeps {0,1,2}; renormalized probs ~ [0.47, 0.35, 0.18];
        # top_p=0.5 then keeps {0,1} (the 0.47 prefix doesn't cover 0.5 yet)
        out = decode.filter_logits(logits, top_k=3, top_p=0.5)
        kept = np.asarray(out[0] > -1e29)
        assert kept.tolist() == [True, True, False, False, False]

    def test_top_p_zero_keeps_most_likely_token(self):
        logits = jnp.array([[1.0, 3.0, 2.0]])
        out = decode.filter_logits(logits, top_p=0.0)
        kept = np.asarray(out[0] > -1e29)
        assert kept.tolist() == [False, True, False]
