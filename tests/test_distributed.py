"""Multi-host gang process-topology derivation (jax.distributed wiring)."""

import pytest

pytest.importorskip("jax")  # jax-less image builds run the scheduler suite

from hivedscheduler_tpu.api import types as api
from hivedscheduler_tpu.parallel.distributed import gang_process_info, initialize_from_gang


def gang_bind_info(nodes):
    return api.PodBindInfo(
        node=nodes[0],
        cell_chain="v5p-64",
        affinity_group_bind_info=[
            api.AffinityGroupMemberBindInfo(pod_placements=[
                api.PodPlacementInfo(physical_node=n, physical_leaf_cell_indices=[0, 1, 2, 3])
                for n in nodes
            ])
        ],
    )


def test_ranks_are_consistent_across_members():
    nodes = ["pod0/2-0-0", "pod0/0-0-0", "pod0/0-2-0", "pod0/2-2-0"]
    bi = gang_bind_info(nodes)
    infos = {n: gang_process_info(bi, n) for n in nodes}
    coordinators = {c for c, _, _ in infos.values()}
    assert coordinators == {"pod0/0-0-0"}  # rank 0 = lexicographically first
    assert sorted(pid for _, pid, _ in infos.values()) == [0, 1, 2, 3]
    assert all(num == 4 for _, _, num in infos.values())


def test_unknown_node_rejected():
    bi = gang_bind_info(["pod0/0-0-0"])
    with pytest.raises(ValueError):
        gang_process_info(bi, "ghost")


def test_multiple_pods_per_node_need_chip_indices():
    # two gang pods share one host: distinct chip grants, distinct ranks
    bi = api.PodBindInfo(
        node="h0", cell_chain="v5e-8",
        affinity_group_bind_info=[
            api.AffinityGroupMemberBindInfo(pod_placements=[
                api.PodPlacementInfo(physical_node="h0",
                                     physical_leaf_cell_indices=[0, 1]),
                api.PodPlacementInfo(physical_node="h0",
                                     physical_leaf_cell_indices=[2, 3]),
            ])
        ],
    )
    with pytest.raises(ValueError, match="pass my_chip_indices"):
        gang_process_info(bi, "h0")
    c0, p0, n0 = gang_process_info(bi, "h0", my_chip_indices=[0, 1])
    c1, p1, n1 = gang_process_info(bi, "h0", my_chip_indices=[3, 2])
    assert (n0, n1) == (2, 2) and {p0, p1} == {0, 1} and c0 == c1 == "h0"


def test_single_host_skips_distributed(monkeypatch):
    monkeypatch.delenv("POD_BIND_INFO", raising=False)
    monkeypatch.delenv("NODE_NAME", raising=False)
    assert initialize_from_gang() == (0, 1)
