"""Cell-tree constructor tests (mirroring the reference's config-as-fake-cluster
strategy, SURVEY.md §4): golden topologies for generic and mesh chains."""

import os

import pytest

from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.algorithm.config_parser import parse_config
from hivedscheduler_tpu.algorithm.mesh import MeshChain
from hivedscheduler_tpu.api.types import MeshSpec

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "config", "design", "tpu-hive.yaml",
)


@pytest.fixture(scope="module")
def parsed():
    return parse_config(load_config(FIXTURE))


def test_chains_present(parsed):
    assert set(parsed.physical_full_list) == {"v4-node-pool", "v5p-64", "v5e-8"}


def test_generic_chain_structure(parsed):
    levels = parsed.chain_levels["v4-node-pool"]
    assert [lv.cell_type for lv in levels] == ["v4-chip", "v4-tray", "v4-node", "v4-node-pool"]
    assert [lv.leaf_cell_number for lv in levels] == [1, 4, 8, 24]
    assert levels[2].is_node_level and not levels[2].is_multi_nodes
    assert levels[3].is_multi_nodes
    full = parsed.physical_full_list["v4-node-pool"]
    assert len(full[1]) == 24 and len(full[2]) == 6 and len(full[3]) == 3 and len(full[4]) == 1
    top = full[4][0]
    assert top.nodes == ["0", "1", "2"]
    assert top.leaf_cell_indices == [-1]
    node = full[3][0]
    assert node.nodes == ["0"]
    assert sorted(node.leaf_cell_indices) == list(range(8))


def test_mesh_chain_structure(parsed):
    levels = parsed.chain_levels["v5p-64"]
    assert [lv.cell_type for lv in levels] == [
        "v5p-chip", "v5p-64-host", "v5p-2x2x2", "v5p-4x4x2", "v5p-64",
    ]
    assert [lv.leaf_cell_number for lv in levels] == [1, 4, 8, 32, 64]
    assert [lv.child_number for lv in levels] == [0, 4, 2, 4, 2]
    full = parsed.physical_full_list["v5p-64"]
    assert len(full[1]) == 64 and len(full[2]) == 16 and len(full[3]) == 8
    assert len(full[4]) == 2 and len(full[5]) == 1
    # host cells map to nodes with 4-chip TPU_VISIBLE_CHIPS index ranges
    host = full[2][0]
    assert host.nodes == [host.address]
    assert sorted(host.leaf_cell_indices) == [0, 1, 2, 3]
    # contiguity: every cell is a contiguous sub-mesh with exact tiling
    for level in range(1, 6):
        for cell in full[level]:
            assert cell.mesh_origin is not None and cell.mesh_shape is not None
    top = full[5][0]
    assert top.mesh_shape == (4, 4, 4)
    assert len(top.nodes) == 16


def test_mesh_pinned_cell(parsed):
    pins = {pid: c for vc_pins in parsed.physical_pinned_cells.values() for pid, c in vc_pins.items()}
    assert "pin1" in pins
    pin = pins["pin1"]
    assert pin.chain == "v5p-64"
    assert pin.level == 3 and pin.mesh_origin == (0, 0, 0) and pin.mesh_shape == (2, 2, 2)
    assert pin.pinned


def test_single_host_mesh_chain(parsed):
    levels = parsed.chain_levels["v5e-8"]
    assert [lv.cell_type for lv in levels] == ["v5e-chip", "v5e-8"]
    assert levels[1].is_node_level and not levels[1].is_multi_nodes
    full = parsed.physical_full_list["v5e-8"]
    assert len(full[1]) == 8 and len(full[2]) == 1
    top = full[2][0]
    assert top.nodes == ["v5e-host0/0-0"]
    assert sorted(top.leaf_cell_indices) == list(range(8))


def test_virtual_cells(parsed):
    assert parsed.vc_free_cell_num["vc1"]["v5p-64"] == {4: 1, 3: 1}  # incl. pinned
    assert parsed.vc_free_cell_num["vc1"]["v4-node-pool"] == {3: 2}
    assert parsed.vc_free_cell_num["vc2"]["v5p-64"] == {3: 2}
    assert parsed.vc_free_cell_num["vc2"]["v5e-8"] == {2: 1}
    # vc1's non-pinned free list has one v5p-4x4x2 root whose tree reaches chips
    free = parsed.virtual_non_pinned_free["vc1"]["v5p-64"]
    (root,) = free[4]
    assert root.total_leaf_cell_num == 32
    assert root.preassigned_cell is root
    leaves = root.children[0].children[0].children
    assert all(lv.level == 1 for lv in leaves)
    # pinned virtual tree exists for vc1
    assert "pin1" in parsed.virtual_pinned_cells["vc1"]
    assert len(parsed.virtual_pinned_cells["vc1"]["pin1"][1]) == 8


def test_leaf_type_maps(parsed):
    assert parsed.leaf_cell_type_to_chain["v5p-chip"] == ["v5p-64"]
    assert parsed.leaf_cell_type_to_chain["v4-chip"] == ["v4-node-pool"]
    assert parsed.cell_level_to_leaf_cell_num["v5p-64"][4] == 32
    assert parsed.cell_level_to_type["v5p-64"][3] == "v5p-2x2x2"


def test_mesh_validation_errors():
    with pytest.raises(ValueError):
        MeshChain("bad", MeshSpec(topology=(4, 4), chip_type="c", host_shape=(3, 3)))
    with pytest.raises(ValueError):
        MeshChain(
            "bad2",
            MeshSpec(
                topology=(4, 4),
                chip_type="c",
                host_shape=(2, 2),
                levels=[type("L", (), {"name": "x", "shape": (3, 2)})()],
            ),
        )


def test_host_name_format_k8s_legal():
    """hostNameFormat maps mesh hosts onto real (DNS-1123-legal) node names —
    required for any real control plane, where the default '{cell}/{coords}'
    is rejected by the ApiServer. Bad formats fail at parse time."""
    from hivedscheduler_tpu.api.config import Config, new_config
    from hivedscheduler_tpu.api.types import PhysicalClusterSpec

    def cfg(fmt):
        return new_config(Config(physical_cluster=PhysicalClusterSpec.from_dict({
            "cellTypes": {"m8": {"mesh": {
                "topology": [4, 2], "chipType": "chip", "hostShape": [2, 2],
                "levels": [{"name": "m-2x2", "shape": [2, 2]}],
                **({"hostNameFormat": fmt} if fmt else {}),
            }}},
            "physicalCells": [{"cellType": "m8", "cellAddress": "pod0"}],
        })))

    parsed = parse_config(cfg("tpu-{coords}.gke.internal"))
    top = parsed.physical_full_list["m8"][max(parsed.physical_full_list["m8"])][0]
    assert sorted(top.nodes) == ["tpu-0-0.gke.internal", "tpu-2-0.gke.internal"]
    # round-trips through the spec serializer
    spec = cfg("tpu-{coords}.gke.internal").physical_cluster
    assert spec.to_dict()["cellTypes"]["m8"]["mesh"]["hostNameFormat"]
    with pytest.raises(ValueError, match="coords"):
        parse_config(cfg("static-name"))
    with pytest.raises(ValueError, match="legal"):
        parse_config(cfg("UPPER-{coords}"))
    with pytest.raises(ValueError, match="legal"):
        parse_config(cfg("tpu-{coords}..internal"))  # empty DNS label
    with pytest.raises(ValueError, match="legal"):
        parse_config(cfg("x" * 70 + "-{coords}"))  # label > 63 chars
    with pytest.raises(ValueError, match="placeholder"):
        parse_config(cfg("tpu-{rack}-{coords}"))

    # two physical cells of one chain must not derive the same node names
    from hivedscheduler_tpu.api.config import Config, new_config as _nc

    def two_cells(fmt):
        return new_config(Config(physical_cluster=PhysicalClusterSpec.from_dict({
            "cellTypes": {"m8": {"mesh": {
                "topology": [4, 2], "chipType": "chip", "hostShape": [2, 2],
                "levels": [{"name": "m-2x2", "shape": [2, 2]}],
                "hostNameFormat": fmt,
            }}},
            "physicalCells": [
                {"cellType": "m8", "cellAddress": "pod0"},
                {"cellType": "m8", "cellAddress": "pod1"},
            ],
        })))

    with pytest.raises(ValueError, match="same node name"):
        parse_config(two_cells("tpu-{coords}"))
    parsed2 = parse_config(two_cells("{cell}-{coords}"))
    tops = parsed2.physical_full_list["m8"][max(parsed2.physical_full_list["m8"])]
    assert {n for t in tops for n in t.nodes} == {
        "pod0-0-0", "pod0-2-0", "pod1-0-0", "pod1-2-0"}
    # default stays the simulation-friendly cell/coords form
    parsed = parse_config(cfg(None))
    top = parsed.physical_full_list["m8"][max(parsed.physical_full_list["m8"])][0]
    assert sorted(top.nodes) == ["pod0/0-0", "pod0/2-0"]


from helpers import V5E32_CELL_TYPES, make_pod, set_healthy_nodes


class TestOddTopologies:
    @staticmethod
    def _config(cell_types, physical_cells, vcs=None):
        from hivedscheduler_tpu.api.config import Config, new_config
        from hivedscheduler_tpu.api.types import (
            PhysicalClusterSpec,
            VirtualClusterSpec,
        )

        return new_config(Config(
            physical_cluster=PhysicalClusterSpec.from_dict(
                {"cellTypes": cell_types, "physicalCells": physical_cells}),
            virtual_clusters={k: VirtualClusterSpec.from_dict(v)
                              for k, v in (vcs or {}).items()},
        ))

    def _parse(self, cell_types, physical_cells, vcs=None):
        return parse_config(self._config(cell_types, physical_cells, vcs))

    def test_2d_v5e_32(self):
        # v5e-32: 4x8 2D mesh, 4 hosts of 2x4
        p = self._parse(
            V5E32_CELL_TYPES,
            [{"cellType": "v5e-32", "cellAddress": "s0"}],
        )
        full = p.physical_full_list["v5e-32"]
        assert len(full[1]) == 32 and len(full[2]) == 4  # hosts
        assert len(full[3]) == 2 and len(full[4]) == 1   # v5e-16s, top
        # host tiling of the 4x4 level: 2 hosts per v5e-16
        assert p.chain_levels["v5e-32"][2].child_number == 2

    def test_non_power_of_two_tiling(self):
        # 6x3 mesh with 2x3 hosts and a 6x3 top: 3 hosts
        p = self._parse(
            {"m": {"mesh": {"topology": [6, 3], "chipType": "c",
                            "hostShape": [2, 3]}}},
            [{"cellType": "m", "cellAddress": "x"}],
        )
        full = p.physical_full_list["m"]
        assert len(full[1]) == 18 and len(full[2]) == 3 and len(full[3]) == 1
        assert p.chain_levels["m"][2].child_number == 3

    def test_schedule_on_2d_mesh(self):
        from hivedscheduler_tpu.algorithm import HivedAlgorithm
        from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
        from hivedscheduler_tpu.runtime.utils import new_binding_pod

        cfg = self._config(
            V5E32_CELL_TYPES,
            [{"cellType": "v5e-32", "cellAddress": "s0"}],
            vcs={"vc": {"virtualCells": [{"cellType": "v5e-32.v5e-16",
                                          "cellNumber": 2}]}},
        )
        h = HivedAlgorithm(cfg)
        nodes = set_healthy_nodes(h)
        spec = {"virtualCluster": "vc", "priority": 0, "chipNumber": 8,
                "affinityGroup": {"name": "g", "members": [
                    {"podNumber": 2, "chipNumber": 8}]}}
        origins = []
        for i in range(2):
            pod = make_pod(f"g-{i}", spec)
            r = h.schedule(pod, nodes, FILTERING_PHASE)
            assert r.pod_bind_info is not None
            h.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
            origins.append(tuple(int(x) for x in
                                 r.pod_bind_info.node.split("/")[-1].split("-")))
        # the two 8-chip hosts form one contiguous v5e-16 (4x4) tile
        assert {o[1] for o in origins} in ({0}, {4}) and {o[0] for o in origins} == {0, 2}


class TestExampleConfigsValid:
    """Every shipped example config must construct a working scheduler —
    including the scheduler config embedded in the deploy manifest."""

    def test_design_fixture(self):
        from hivedscheduler_tpu.algorithm import HivedAlgorithm

        HivedAlgorithm(load_config(FIXTURE))

    def test_gnarly_fixture(self):
        from hivedscheduler_tpu.algorithm import HivedAlgorithm

        path = os.path.join(os.path.dirname(FIXTURE), "tpu-hive-gnarly.yaml")
        h = HivedAlgorithm(load_config(path))
        assert set(h.full_cell_list) == {
            "v5p-8x4x2", "v5e-16f", "g-pool", "ct-node", "3-mx-node"}

    def test_fleet_fixture(self):
        """fleet.yaml boots BOTH ways: the scheduler side constructs the
        algorithm, the serving side parses the `fleet:` section."""
        from hivedscheduler_tpu.algorithm import HivedAlgorithm
        from hivedscheduler_tpu.fleet import FleetConfig

        path = os.path.join(os.path.dirname(FIXTURE), "fleet.yaml")
        h = HivedAlgorithm(load_config(path))
        assert "v5e-16f" in h.full_cell_list
        fc = FleetConfig.from_yaml(path)
        assert fc is not None and fc.disaggregate
        assert fc.autoscale_policy().max_replicas == fc.max_replicas

    def test_deploy_manifest_embedded_config(self):
        import yaml

        from hivedscheduler_tpu.api.config import Config, new_config
        from hivedscheduler_tpu.algorithm import HivedAlgorithm

        path = os.path.join(os.path.dirname(FIXTURE), "..", "..", "run",
                            "deploy.yaml")
        docs = list(yaml.safe_load_all(open(path)))
        cm = next(d for d in docs if d and d.get("kind") == "ConfigMap")
        cfg = Config.from_dict(yaml.safe_load(cm["data"]["tpu-hive.yaml"]))
        h = HivedAlgorithm(new_config(cfg))
        assert "v5p-256" in h.full_cell_list
        # the extender policy must point at the routes we serve
        policy = __import__("json").loads(cm["data"]["policy.cfg"])
        ext = policy["extenders"][0]
        assert ext["filterVerb"] == "filter" and ext["bindVerb"] == "bind"
        assert ext["preemptVerb"] == "preempt"

    def test_kind_e2e_fixtures_consistent(self):
        """The kind-e2e manifests (example/run/kind-e2e/) must stay
        internally consistent without a cluster: the embedded config boots,
        its hostNameFormat-derived node names equal the fake kwok Node
        names, and the exact test pod schedules + binds onto them through
        the full algorithm (so the CI job can only fail on genuinely
        control-plane concerns: RBAC, wire serialization, Bind merge)."""
        import yaml

        from hivedscheduler_tpu.api import constants as C
        from hivedscheduler_tpu.api.config import Config, new_config
        from hivedscheduler_tpu.algorithm import HivedAlgorithm
        from hivedscheduler_tpu.k8s import serde
        from hivedscheduler_tpu.k8s.types import Node
        from hivedscheduler_tpu.runtime.types import FILTERING_PHASE
        from hivedscheduler_tpu.runtime.utils import new_binding_pod

        base = os.path.join(os.path.dirname(FIXTURE), "..", "..", "run",
                            "kind-e2e")
        docs = list(yaml.safe_load_all(open(os.path.join(base, "manifests.yaml"))))
        cm = next(d for d in docs if d and d.get("kind") == "ConfigMap")
        cfg = Config.from_dict(yaml.safe_load(cm["data"]["config.yaml"]))
        algo = HivedAlgorithm(new_config(cfg))
        derived = sorted({n for ccl in algo.full_cell_list.values()
                          for c in ccl[max(ccl)] for n in c.nodes})
        fake_nodes = [d["metadata"]["name"] for d in
                      yaml.safe_load_all(open(os.path.join(base, "fake-nodes.yaml")))
                      if d]
        assert derived == sorted(fake_nodes), (derived, fake_nodes)
        # RBAC covers exactly what the REST client needs
        role = next(d for d in docs if d and d.get("kind") == "ClusterRole")
        rules = {(r0, v) for r in role["rules"]
                 for r0 in r["resources"] for v in r["verbs"]}
        assert {("nodes", "watch"), ("pods", "watch"),
                ("pods/binding", "create")} <= rules
        # the shipped pod schedules and binds on this config
        pod_doc = yaml.safe_load(open(os.path.join(base, "test-pod.yaml")))
        pod = serde.pod_from_k8s(pod_doc)
        for n in fake_nodes:
            algo.add_node(Node(name=n))
        result = algo.schedule(pod, fake_nodes, FILTERING_PHASE)
        assert result.pod_bind_info is not None, result.pod_wait_info
        assert result.pod_bind_info.node in fake_nodes
        bp = new_binding_pod(pod, result.pod_bind_info)
        assert bp.annotations[C.ANNOTATION_POD_CHIP_ISOLATION]
        algo.add_allocated_pod(bp)

    def test_modern_deploy_manifest(self):
        """deploy-modern.yaml replaces the removed-in-1.23 Policy file with a
        KubeSchedulerConfiguration; its extender block must carry the same
        contract (verbs matching our routes, managed resource matching the
        admission predicate) and its embedded scheduler config must boot."""
        import yaml

        from hivedscheduler_tpu.api import constants as C
        from hivedscheduler_tpu.api.config import Config, new_config
        from hivedscheduler_tpu.algorithm import HivedAlgorithm

        path = os.path.join(os.path.dirname(FIXTURE), "..", "..", "run",
                            "deploy-modern.yaml")
        docs = list(yaml.safe_load_all(open(path)))
        cm = next(d for d in docs if d and d.get("kind") == "ConfigMap")
        cfg = Config.from_dict(yaml.safe_load(cm["data"]["tpu-hive.yaml"]))
        h = HivedAlgorithm(new_config(cfg))
        assert "v5p-256" in h.full_cell_list

        ksc = yaml.safe_load(cm["data"]["kube-scheduler-vc-research.yaml"])
        assert ksc["kind"] == "KubeSchedulerConfiguration"
        assert ksc["apiVersion"].startswith("kubescheduler.config.k8s.io/")
        names = [p["schedulerName"] for p in ksc["profiles"]]
        assert names == ["tpu-hive-vc-research"]
        ext = ksc["extenders"][0]
        # urlPrefix + verb must reproduce the routes the webserver serves
        for verb, route in (("filterVerb", C.FILTER_PATH),
                            ("bindVerb", C.BIND_PATH),
                            ("preemptVerb", C.PREEMPT_PATH)):
            assert ext["urlPrefix"].endswith(C.EXTENDER_PATH)
            assert route == C.EXTENDER_PATH + "/" + ext[verb]
        assert ext["ignorable"] is False and ext["nodeCacheCapable"] is True
        assert (ext["managedResources"][0]["name"]
                == C.RESOURCE_NAME_POD_SCHEDULING_ENABLE)
        # every kube-scheduler pod must consume a config file that exists in
        # the ConfigMap (the legacy --policy-configmap flag is gone)
        for d in docs:
            if d and d.get("kind") == "StatefulSet" and "kube-scheduler" in d["metadata"]["name"]:
                cmd = d["spec"]["template"]["spec"]["containers"][0]["command"]
                cfg_flags = [a for a in cmd if a.startswith("--config=")]
                assert cfg_flags, cmd
                fname = cfg_flags[0].split("/")[-1]
                assert fname in cm["data"], fname


def test_sku_types_round_trip():
    """HiveD configs carrying skuTypes (external-tooling metadata) must
    round-trip even though the scheduler ignores them."""
    from hivedscheduler_tpu.api.types import PhysicalClusterSpec

    d = {
        "skuTypes": {"v5p": {"cpu": 10, "memory": "160Gi", "tpu": 1}},
        "cellTypes": {"node": {"childCellType": "chip", "childCellNumber": 4,
                               "isNodeLevel": True}},
        "physicalCells": [{"cellType": "node", "cellAddress": "n0"}],
    }
    spec = PhysicalClusterSpec.from_dict(d)
    assert spec.sku_types["v5p"]["memory"] == "160Gi"
    assert spec.to_dict()["skuTypes"] == d["skuTypes"]
