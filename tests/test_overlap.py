"""Overlapped tensor parallelism (the collective-matmul path).

The load-bearing property is differential parity: HIVED_OVERLAP=1 (the
default when applicable) must compute exactly what the HIVED_OVERLAP=0
GSPMD reference computes — bit-identical forward at tp=2 (where the only
cross-device reduction is a commutative two-term sum) and allclose
gradients — because the overlapped path is a SCHEDULE change (ICI hops
pipelined under MXU work), never a numerics change. Inputs are placed on
the training shardings explicitly, as every production entry point does:
with auto-chosen shardings the two jits may pick different GSPMD
partitionings and drift by ulps for reasons unrelated to the overlap.

Also covers the gate itself (applicability reasons, cfg.overlap=True
raising, the env kill switch), the remat-policy override of the train-step
factory, and the tier-1 compile+step smoke of the overlapped train step on
the virtual CPU mesh (kept at 4 devices: the 1-core box's 40 s collective
rendezvous limit — CLAUDE.md)."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import transformer as tm  # noqa: E402
from hivedscheduler_tpu.parallel import topology  # noqa: E402
from hivedscheduler_tpu.parallel.train import (  # noqa: E402
    _shardings,
    loss_fn,
    make_sharded_train_step,
)


def cpu_mesh(axes):
    return topology.make_mesh(axes, topology.get_devices(axes.size))


def cfg_of(**kw):
    base = dict(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=128, max_seq_len=64, dtype=jnp.float32)
    base.update(kw)
    return tm.TransformerConfig(**base)


def placed(cfg, mesh, seed=0, batch=4, seq=32):
    """Params + tokens on the explicit training shardings (the production
    layout; see module docstring for why this matters for bit parity)."""
    params = tm.init_params(cfg, jax.random.PRNGKey(seed))
    tokens = jax.random.randint(
        jax.random.PRNGKey(seed + 1), (batch, seq), 0, cfg.vocab_size,
        jnp.int32,
    )
    psh, tsh = _shardings(cfg, mesh)
    return jax.device_put(params, psh), jax.device_put(tokens, tsh)


def fwd_both(cfg, mesh, monkeypatch, batch=4, seq=32):
    params, tokens = placed(cfg, mesh, batch=batch, seq=seq)
    monkeypatch.setenv("HIVED_OVERLAP", "0")
    ref = np.asarray(
        jax.jit(lambda p, t: tm.forward(p, t, cfg, mesh))(params, tokens)
    )
    monkeypatch.delenv("HIVED_OVERLAP")
    assert tm._use_overlap(cfg, mesh, seq, batch), "gate must engage"
    out = np.asarray(
        jax.jit(lambda p, t: tm.forward(p, t, cfg, mesh))(params, tokens)
    )
    return ref, out


class TestOverlapGate:
    def test_applicability_reasons(self):
        mesh = cpu_mesh(topology.MeshAxes(tp=2))
        ok, _ = tm.overlap_applicable(cfg_of(), mesh, 32, 4)
        assert ok
        for bad, frag in (
            (dict(n_experts=4), "MoE"),
            (dict(lora_rank=2), "LoRA"),
            (dict(pipeline_microbatches=2), "pipeline"),
            (dict(d_ff=129), "divide"),
        ):
            ok, reason = tm.overlap_applicable(cfg_of(**bad), mesh, 32, 4)
            assert not ok and frag in reason, (bad, reason)
        ok, reason = tm.overlap_applicable(cfg_of(), mesh, 33, 4)
        assert not ok and "sequence" in reason
        ok, reason = tm.overlap_applicable(cfg_of(), None)
        assert not ok
        # tp=1: nothing to overlap
        ok, reason = tm.overlap_applicable(
            cfg_of(), cpu_mesh(topology.MeshAxes(dp=2)), 32, 4
        )
        assert not ok and "tp" in reason

    def test_env_kill_switch_and_explicit_opt(self, monkeypatch):
        mesh = cpu_mesh(topology.MeshAxes(tp=2))
        monkeypatch.setenv("HIVED_OVERLAP", "0")
        assert not tm._use_overlap(cfg_of(overlap=True), mesh, 32, 4)
        monkeypatch.delenv("HIVED_OVERLAP")
        assert not tm._use_overlap(cfg_of(overlap=False), mesh, 32, 4)
        assert tm._use_overlap(cfg_of(), mesh, 32, 4)
        with pytest.raises(ValueError, match="overlap"):
            tm._use_overlap(cfg_of(overlap=True, n_experts=4), mesh, 32, 4)


class TestOverlapParity:
    def test_forward_bit_parity_tp2(self, monkeypatch):
        """tp=2: the row-parallel partials sum two commutative terms, so
        the overlapped forward must BIT-match the reference."""
        mesh = cpu_mesh(topology.MeshAxes(tp=2))
        ref, out = fwd_both(cfg_of(), mesh, monkeypatch)
        assert (ref == out).all(), np.abs(ref - out).max()

    @pytest.mark.slow
    def test_forward_bit_parity_tp2_with_dp(self, monkeypatch):
        """Batch sharding composes bit-exactly: dp only splits the batch
        dim, which no reduction crosses. (slow: tier-1 keeps the tp2 bit
        test + the dp=2 x tp=2 train-step smoke as representatives)"""
        mesh = cpu_mesh(topology.MeshAxes(dp=2, tp=2))
        ref, out = fwd_both(cfg_of(), mesh, monkeypatch)
        assert (ref == out).all(), np.abs(ref - out).max()

    @pytest.mark.slow
    def test_forward_parity_with_fsdp_allclose(self, monkeypatch):
        """fsdp composes allclose, not bitwise: the reference GSPMD path
        may CONTRACT the fsdp-sharded weight dim locally and all-reduce
        the partial dots, while the overlapped body all-gathers the weight
        and runs the full dot (ZeRO per-use gather) — two associations of
        the same sum."""
        mesh = cpu_mesh(topology.MeshAxes(dp=2, fsdp=2, tp=2))
        ref, out = fwd_both(cfg_of(), mesh, monkeypatch)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    @pytest.mark.slow
    def test_forward_parity_tp4_allclose(self, monkeypatch):
        """tp=4: the ring accumulates the four row-parallel partials in a
        different (device-dependent) order than the reference all-reduce,
        so parity is allclose, not bitwise."""
        mesh = cpu_mesh(topology.MeshAxes(tp=4))
        ref, out = fwd_both(cfg_of(n_kv_heads=4), mesh, monkeypatch)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    @pytest.mark.slow
    def test_forward_parity_with_sp_ring(self, monkeypatch):
        """tp=2 x sp=2 with ring attention: the overlapped body runs the
        manual ring locals over sp inside the same shard_map."""
        mesh = cpu_mesh(topology.MeshAxes(tp=2, sp=2))
        cfg = cfg_of(attn_impl="ring")
        ref, out = fwd_both(cfg, mesh, monkeypatch)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_grads_allclose_tp2(self, monkeypatch):
        mesh = cpu_mesh(topology.MeshAxes(tp=2))
        cfg = cfg_of()
        params, tokens = placed(cfg, mesh)
        grad = jax.jit(
            jax.grad(lambda p, t: loss_fn(p, t, cfg, mesh))
        )
        monkeypatch.setenv("HIVED_OVERLAP", "0")
        ref = grad(params, tokens)
        monkeypatch.delenv("HIVED_OVERLAP")
        out = jax.jit(
            jax.grad(lambda p, t: loss_fn(p, t, cfg, mesh))
        )(params, tokens)
        flat_r, _ = jax.tree.flatten(ref)
        flat_o, _ = jax.tree.flatten(out)
        for r, o in zip(flat_r, flat_o):
            np.testing.assert_allclose(
                np.asarray(o), np.asarray(r), atol=5e-5, rtol=1e-5
            )


class TestOverlapTrainStep:
    def test_overlapped_train_step_smoke(self):
        """Tier-1 smoke: build + compile + step the overlapped train step
        on a dp=2 x tp=2 CPU mesh (4 devices — inside the 1-core box's
        rendezvous budget). The loss must be finite and decrease."""
        assert os.environ.get("HIVED_OVERLAP", "") != "0"
        cfg = cfg_of()
        mesh = cpu_mesh(topology.MeshAxes(dp=2, tp=2))
        step, init_fn, token_sharding = make_sharded_train_step(cfg, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                               cfg.vocab_size),
            token_sharding,
        )
        losses = []
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]


class TestTrainCliWiring:
    def test_train_cli_overlap_and_remat_policy(self):
        """--overlap and --remat-policy must be reachable from
        `python -m hivedscheduler_tpu.train` (the recurring
        features-unreachable-from-the-CLI blind spot)."""
        from hivedscheduler_tpu import train as train_cli

        rc = train_cli.main([
            "--steps", "2", "--batch", "4", "--seq-len", "32",
            "--d-model", "32", "--n-layers", "2", "--n-heads", "4",
            "--d-ff", "64", "--vocab-size", "64", "--tp", "2",
            "--fsdp", "1", "--overlap", "--remat-policy", "dots",
            "--log-every", "1",
        ])
        assert rc == 0

    def test_train_cli_overlap_errors_when_inapplicable(self, capsys):
        from hivedscheduler_tpu import train as train_cli

        with pytest.raises(SystemExit):
            # tp=1: nothing to overlap — --overlap must fail fast, not
            # silently run the reference path
            train_cli.main([
                "--steps", "1", "--batch", "2", "--seq-len", "32",
                "--d-model", "32", "--n-layers", "1", "--n-heads", "4",
                "--d-ff", "64", "--vocab-size", "64", "--overlap",
            ])


class TestRematPolicy:
    @pytest.mark.slow  # tier-1 wall-time budget (ROADMAP maintenance): heavy variant; fast cousins stay tier-1
    def test_remat_policies_compute_identical_step(self):
        """The remat policy trades recompute for HBM only: one train step
        under each policy must produce the SAME loss and (numerically)
        the same updated parameters as blanket remat."""
        cfg = cfg_of()
        mesh = cpu_mesh(topology.MeshAxes())  # 1 device: no rendezvous

        def one_step(remat_policy):
            step, init_fn, token_sharding = make_sharded_train_step(
                cfg, mesh, remat_policy=remat_policy
            )
            params, opt_state = init_fn(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                   cfg.vocab_size),
                token_sharding,
            )
            params, _, loss = step(params, opt_state, tokens)
            return float(loss), params

        loss_full, params_full = one_step("full")
        for policy in ("dots", "none"):
            loss_p, params_p = one_step(policy)
            assert loss_full == loss_p, (policy, loss_full, loss_p)
            for a, b in zip(jax.tree.leaves(params_full),
                            jax.tree.leaves(params_p)):
                np.testing.assert_allclose(
                    np.asarray(b), np.asarray(a), atol=1e-6, err_msg=policy
                )

    def test_remat_policy_validated(self):
        with pytest.raises(ValueError, match="remat_policy"):
            make_sharded_train_step(
                cfg_of(), cpu_mesh(topology.MeshAxes()),
                remat_policy="everything",
            )
