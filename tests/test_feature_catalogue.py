"""The runnable feature catalogue (example/feature/file/) must stay live:
every config boots a scheduler, every job manifest parses into schedulable
pods, and each feature's walkthrough reproduces its documented behavior —
the automated analogue of the reference's manual repro steps
(/root/reference/example/feature/README.md:7-222, hived-config-*.yaml).
"""

import glob
import os

import pytest
import yaml

from hivedscheduler_tpu.algorithm import HivedAlgorithm
from hivedscheduler_tpu.api import constants as C
from hivedscheduler_tpu.api.config import load_config
from hivedscheduler_tpu.k8s.types import Container, Node, Pod
from hivedscheduler_tpu.runtime.types import FILTERING_PHASE, PREEMPTING_PHASE
from hivedscheduler_tpu.runtime.utils import new_binding_pod

from helpers import set_healthy_nodes

FILE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "example", "feature", "file",
)


def boot(config_name):
    algo = HivedAlgorithm(load_config(os.path.join(FILE_DIR, config_name)))
    nodes = set_healthy_nodes(algo)
    return algo, nodes


def load_job_pods(job_name):
    """Expand a catalogue job manifest into the Pod objects the scheduler
    sees: one per Job completion (or the bare Pod), annotation verbatim."""
    path = os.path.join(FILE_DIR, job_name)
    pods = []
    for doc in yaml.safe_load_all(open(path)):
        if not doc:
            continue
        if doc["kind"] == "Pod":
            metas = [(doc["metadata"]["name"], doc["metadata"])]
            spec = doc["spec"]
        else:
            assert doc["kind"] == "Job", doc["kind"]
            n = doc["spec"]["completions"]
            tmpl = doc["spec"]["template"]
            metas = [(f'{doc["metadata"]["name"]}-{i}', tmpl["metadata"])
                     for i in range(n)]
            spec = tmpl["spec"]
        for pod_name, meta in metas:
            ann = meta["annotations"][C.ANNOTATION_POD_SCHEDULING_SPEC]
            limits = spec["containers"][0]["resources"]["limits"]
            pods.append(Pod(
                name=pod_name, uid=pod_name,
                annotations={C.ANNOTATION_POD_SCHEDULING_SPEC: ann},
                containers=[Container(resource_limits=dict(limits))],
            ))
    return pods


def place_gang(algo, nodes, pods, allow_preempt=False):
    """Schedule+allocate a whole gang; returns list of (pod, bind_info) or
    None if any member waits. Victims are killed instantly when
    ``allow_preempt``."""
    bound = []
    for pod in pods:
        r = algo.schedule(pod, nodes, FILTERING_PHASE)
        if r.pod_preempt_info is not None and allow_preempt:
            for _ in range(64):
                for victim in r.pod_preempt_info.victim_pods:
                    algo.delete_allocated_pod(victim)
                r = algo.schedule(pod, nodes, PREEMPTING_PHASE)
                if r.pod_preempt_info is None:
                    break
        if r.pod_bind_info is None:
            for bp in bound:
                algo.delete_allocated_pod(bp)
            return None
        bp = new_binding_pod(pod, r.pod_bind_info)
        algo.add_allocated_pod(bp)
        bound.append(bp)
    return bound


ALL_CONFIGS = sorted(
    os.path.basename(p) for p in glob.glob(os.path.join(FILE_DIR, "config-*.yaml"))
)
ALL_JOBS = sorted(
    os.path.basename(p) for p in glob.glob(os.path.join(FILE_DIR, "job-*.yaml"))
)


def test_catalogue_is_complete():
    # every feature section in the README links at least one runnable file
    readme = open(os.path.join(FILE_DIR, "..", "README.md")).read()
    assert len(ALL_CONFIGS) >= 12, ALL_CONFIGS
    assert len(ALL_JOBS) >= 18, ALL_JOBS
    for name in ALL_CONFIGS + ALL_JOBS:
        assert name in readme, f"{name} not linked from example/feature/README.md"


@pytest.mark.parametrize("config_name", ALL_CONFIGS)
def test_config_boots(config_name):
    algo, nodes = boot(config_name)
    assert nodes


@pytest.mark.parametrize("job_name", ALL_JOBS)
def test_job_parses(job_name):
    pods = load_job_pods(job_name)
    assert pods
    from hivedscheduler_tpu.runtime.utils import extract_pod_scheduling_spec

    for pod in pods:
        spec = extract_pod_scheduling_spec(pod)
        assert spec.virtual_cluster and spec.leaf_cell_number > 0


class TestFeatureWalkthroughs:
    def test_vc_safety(self):
        # vc1 saturates its half with 1-chip pods; vc2's contiguous 4x2
        # gang must still place (zero cross-VC fragmentation)
        algo, nodes = boot("config-vc-safety.yaml")
        frag = place_gang(algo, nodes, load_job_pods("job-safety-frag.yaml"))
        assert frag is not None and len(frag) == 8
        gang = place_gang(algo, nodes, load_job_pods("job-safety-gang.yaml"))
        assert gang is not None and len(gang) == 2

    def test_pinned_cells(self):
        algo, nodes = boot("config-pinned.yaml")
        pinned = place_gang(algo, nodes, load_job_pods("job-pinned.yaml"))
        assert pinned is not None
        # the pinned 2x2x2 sits at origin: both hosts are 0-*-* addresses
        for bp in pinned:
            assert bp.node_name.split("/")[-1].startswith("0-"), bp.node_name
        # without the pin, the job lands on vc1's regular cells, never on
        # the pinned sub-cube's hosts (0-0-*)
        unpinned = place_gang(algo, nodes, load_job_pods("job-unpinned.yaml"))
        assert unpinned is not None
        for bp in unpinned:
            assert not bp.node_name.split("/")[-1].startswith("0-0-"), bp.node_name

    def test_chip_type(self):
        algo, nodes = boot("config-chip-type.yaml")
        typed = place_gang(algo, nodes, load_job_pods("job-typed-v5e.yaml"))
        assert typed is not None
        assert all("v5e" in bp.node_name for bp in typed)
        for bp in typed:
            algo.delete_allocated_pod(bp)
        untyped = place_gang(algo, nodes, load_job_pods("job-untyped.yaml"))
        assert untyped is not None  # fills both generations
        kinds = {bp.node_name.split("-")[0] for bp in untyped}
        assert kinds == {"v4", "v5e"}, kinds

    def test_gang_all_or_nothing(self):
        algo, nodes = boot("config-gang.yaml")
        # 6 > the VC's 4 chips: whole gang waits...
        assert place_gang(algo, nodes, load_job_pods("job-gang-6.yaml")) is None
        # ...and does not head-of-line-block the 4-pod gang
        assert place_gang(algo, nodes, load_job_pods("job-gang-4.yaml")) is not None

    def test_incremental(self):
        algo, nodes = boot("config-gang.yaml")
        placed = waiting = 0
        for pod in load_job_pods("job-incremental-6.yaml"):
            r = algo.schedule(pod, nodes, FILTERING_PHASE)
            if r.pod_bind_info is None:
                waiting += 1
            else:
                algo.add_allocated_pod(new_binding_pod(pod, r.pod_bind_info))
                placed += 1
        assert (placed, waiting) == (4, 2)

    def test_guaranteed_and_opportunistic(self):
        algo, nodes = boot("config-priority.yaml")
        # opportunistic gang may borrow the whole host (8 chips > 4 guaranteed)
        oppo = place_gang(algo, nodes, load_job_pods("job-opportunistic.yaml"))
        assert oppo is not None and len(oppo) == 2
        # the guaranteed job reclaims its quota by preempting one OT pod
        guar = place_gang(algo, nodes, load_job_pods("job-guaranteed.yaml"),
                          allow_preempt=True)
        assert guar is not None

    def test_intra_vc_preemption(self):
        algo, nodes = boot("config-intra-vc-preempt.yaml")
        low = place_gang(algo, nodes, load_job_pods("job-intra-low.yaml"))
        assert low is not None
        high = place_gang(algo, nodes, load_job_pods("job-intra-high.yaml"),
                          allow_preempt=True)
        assert high is not None

    def test_inter_vc_preemption(self):
        algo, nodes = boot("config-inter-vc-preempt.yaml")
        oppo = place_gang(algo, nodes, load_job_pods("job-inter-oppo.yaml"))
        assert oppo is not None  # vc2 borrows vc1's idle guarantee
        guar = place_gang(algo, nodes,
                          load_job_pods("job-inter-guaranteed.yaml"),
                          allow_preempt=True)
        assert guar is not None

    def test_lazy_preemption(self):
        algo, nodes = boot("config-lazy-preempt.yaml")
        victim = place_gang(algo, nodes, load_job_pods("job-lazy-victim.yaml"))
        assert victim is not None
        # free space exists elsewhere, so the lazy preemptor downgrades the
        # victim instead of killing it: no preempt info, both keep running
        pre = place_gang(algo, nodes, load_job_pods("job-lazy-preemptor.yaml"))
        assert pre is not None
        groups = {g.name for g in algo.affinity_groups.values()}
        assert {"default/lazy-victim", "default/lazy-preemptor"} <= groups

    def test_topology_aware_contiguous(self):
        algo, nodes = boot("config-topology.yaml")
        gang = place_gang(algo, nodes, load_job_pods("job-topo-16.yaml"))
        assert gang is not None
        # 4 pods x 4 chips: one contiguous sub-mesh = exactly 4 distinct
        # hosts whose origins span an aligned 4x2x2 or 2x4x2... verify the
        # bounding box of host origins covers exactly 16 chips
        coords = []
        for bp in gang:
            origin = tuple(
                int(x) for x in bp.node_name.split("/")[-1].split("-")
            )
            coords.append(origin)
        assert len(set(coords)) == 4
        los = [min(c[i] for c in coords) for i in range(3)]
        his = [max(c[i] for c in coords) for i in range(3)]
        # host shape (2,2,1): bounding box of origins + host extent
        extent = [(hi - lo + hs) for lo, hi, hs in zip(los, his, (2, 2, 1))]
        vol = extent[0] * extent[1] * extent[2]
        assert vol == 16, (coords, extent)

    def test_work_preserving_reconfiguration(self):
        algo, nodes = boot("config-reconfig-before.yaml")
        gang = place_gang(algo, nodes, load_job_pods("job-reconfig.yaml"))
        assert gang is not None
        placements = {bp.name: bp.node_name for bp in gang}
        # scheduler restarts with the grown cluster; allocated pods replay
        algo2, nodes2 = boot("config-reconfig-after.yaml")
        for bp in gang:
            algo2.add_allocated_pod(bp)
        # the replayed group's placement in algo2's OWN state matches the
        # pre-restart node set exactly (not just the input objects)
        replayed = algo2.get_affinity_group("default/reconfig")
        assert set(replayed.status.physical_placement) == set(placements.values())
        # ...and the chips they occupy are not handed out again: a new gang
        # lands on disjoint hosts
        again = load_job_pods("job-reconfig.yaml")
        for p in again:
            p.name = p.uid = p.name + "-again"
            ann = p.annotations[C.ANNOTATION_POD_SCHEDULING_SPEC]
            p.annotations[C.ANNOTATION_POD_SCHEDULING_SPEC] = ann.replace(
                "default/reconfig", "default/reconfig-again")
        gang2 = place_gang(algo2, nodes2, again)
        assert gang2 is not None
        assert not (set(placements.values())
                    & {bp.node_name for bp in gang2})

    def test_bad_hardware_awareness(self):
        algo, nodes = boot("config-bad-hardware.yaml")
        gang = place_gang(algo, nodes, load_job_pods("job-bad-hw.yaml"))
        assert gang is not None
        dead = gang[0].node_name
        algo.delete_node(Node(name=dead))
        # the gang's pod on the dead host reschedules onto healthy cells
        for bp in gang:
            algo.delete_allocated_pod(bp)
        healthy = [n for n in nodes if n != dead]
        gang2 = place_gang(algo, healthy, load_job_pods("job-bad-hw.yaml"))
        assert gang2 is not None
        assert all(bp.node_name != dead for bp in gang2)


class TestMultiChainWalkthrough:
    def test_multichain_relaxes_across_chains(self):
        algo, nodes = boot("config-multichain.yaml")
        gang = place_gang(algo, nodes, load_job_pods("job-multichain.yaml"))
        assert gang is not None and len(gang) == 6
        chains = {bp.node_name.split("/")[0] for bp in gang}
        assert chains == {"a0", "b0"}  # no single 16-chip chain fits 24

    def test_multichain_balanced_policy(self):
        from collections import Counter

        algo, nodes = boot("config-multichain.yaml")
        gang = place_gang(algo, nodes,
                          load_job_pods("job-multichain-balanced.yaml"))
        assert gang is not None and len(gang) == 6
        per_chain = Counter(bp.node_name.split("/")[0] for bp in gang)
        assert sorted(per_chain.values()) == [3, 3], per_chain
