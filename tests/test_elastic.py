"""Elastic resume matrix (ISSUE 10): a checkpoint saved on one
(dp, fsdp, pp, ep, tp, sp) mesh restores onto another.

- same-topology resume stays **bit-exact** (the existing discipline,
  re-asserted through the new commit-marker metadata path);
- cross-topology resume (shrink, grow, tp<->dp reshape on the 8-device CPU
  mesh) pins the loss trajectory **allclose** against the uninterrupted
  run — resharding is exact, only reduction orders change;
- the loader state of record re-slices to a new host width with no sample
  double-trained or skipped;
- `topology.elastic_axes` derives a valid mesh for whatever slice was
  offered, holding the requested degrees as preferences.

The CLI-level end-to-end cousin (kill -9 -> shrink resume -> grow promote
through real `train --elastic` subprocesses) is the slow-marked elastic
chaos episode (tests/test_workload_seeds.py, tools/check_workload_seeds.py).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")
import jax.numpy as jnp  # noqa: E402

from hivedscheduler_tpu.models import transformer as tm  # noqa: E402
from hivedscheduler_tpu.parallel import checkpoint, topology  # noqa: E402
from hivedscheduler_tpu.parallel import data as data_lib  # noqa: E402
from hivedscheduler_tpu.parallel.train import make_sharded_train_step  # noqa: E402

CFG = tm.TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
    max_seq_len=32, dtype=jnp.float32,
)
BATCH, SEQ = 8, 16

# one compiled step per distinct axes layout for the whole module (the
# matrix reuses layouts; recompiling per case would double the wall time)
_SETUPS = {}


def setup_for(axes: topology.MeshAxes):
    if axes not in _SETUPS:
        mesh = topology.make_mesh(axes, topology.get_devices(axes.size))
        _SETUPS[axes] = make_sharded_train_step(CFG, mesh)
    return _SETUPS[axes]


def make_loader(state=None, process_index=0, process_count=1):
    ds = data_lib.synthetic_dataset(CFG.vocab_size, size=1 << 14, seed=7)
    if state is None:
        return data_lib.CheckpointableBatches(
            ds, BATCH, SEQ, seed=5,
            process_index=process_index, process_count=process_count)
    return data_lib.CheckpointableBatches.from_dict(
        state, ds, BATCH, SEQ,
        process_index=process_index, process_count=process_count)


def run_steps(step_fn, tok_sh, params, opt, loader, n):
    losses = []
    for _ in range(n):
        tokens = jax.device_put(next(loader), tok_sh)
        params, opt, loss = step_fn(params, opt, tokens)
        losses.append(float(loss))
    return params, opt, losses


class TestResumeMatrix:
    """Checkpoint at step 2 on the source mesh, then compare steps 3..5 of
    the uninterrupted source run against a fresh incarnation restoring on
    the target mesh — through the same metadata path train.py uses."""

    @pytest.mark.parametrize("source,target,exact", [
        # same topology: bit-exact (the existing kill -9 discipline).
        # Slow: tier-1 wall-time budget (ISSUE 15) — the shrink trajectory
        # below is the tier-1 cousin through the same restore path, and
        # same-topology bit-exactness stays tier-1 via the kill -9
        # bit-exact workload pin (tests/test_checkpoint.py)
        pytest.param(topology.MeshAxes(dp=4), topology.MeshAxes(dp=4),
                     True, marks=pytest.mark.slow),
        # shrink: half the devices
        (topology.MeshAxes(dp=4), topology.MeshAxes(dp=2), False),
        # grow: double the devices (slow: tier-1 wall-time budget,
        # ISSUE 13 — the shrink trajectory above is the tier-1 cousin
        # through the same reshard-on-load path)
        pytest.param(topology.MeshAxes(dp=2), topology.MeshAxes(dp=4),
                     False, marks=pytest.mark.slow),
        # dp -> tp reshape at equal size (slow: tier-1 wall-time budget,
        # ISSUE 13 — the reverse reshape below is the tier-1 cousin
        # through the same reshard-on-load path)
        pytest.param(topology.MeshAxes(dp=4),
                     topology.MeshAxes(dp=2, tp=2), False,
                     marks=pytest.mark.slow),
        # tp -> dp reshape at equal size
        (topology.MeshAxes(dp=2, tp=2), topology.MeshAxes(dp=4), False),
    ], ids=["same-dp4", "shrink-dp4-to-dp2", "grow-dp2-to-dp4",
            "reshape-dp4-to-dp2tp2", "reshape-dp2tp2-to-dp4"])
    def test_trajectory(self, tmp_path, source, target, exact):
        step_fn, init_fn, tok_sh = setup_for(source)
        params, opt = init_fn(jax.random.PRNGKey(0))
        loader = make_loader()
        params, opt, _ = run_steps(step_fn, tok_sh, params, opt, loader, 2)
        meta = checkpoint.train_metadata(
            source, CFG, global_batch=BATCH, seq_len=SEQ)
        checkpoint.save(str(tmp_path), 2, params, opt,
                        extra={"loader": loader.to_dict(), **meta})

        # the uninterrupted reference continues on the source mesh
        _, _, ref_losses = run_steps(step_fn, tok_sh, params, opt, loader, 3)

        # fresh incarnation on the target mesh: validate + restore + resume
        step2_fn, init2_fn, tok_sh2 = setup_for(target)
        params2, opt2 = init2_fn(jax.random.PRNGKey(9))  # overwritten
        saved = checkpoint.read_metadata(str(tmp_path), 2)
        source_mesh = checkpoint.validate_resume_metadata(
            saved, target, CFG, global_batch=BATCH, seq_len=SEQ)
        if source == target:
            assert source_mesh is None  # the bit-exact path
        else:
            assert source_mesh == {
                n: s for n, s in zip(source.names, source.shape)}
        step_no, params2, opt2 = checkpoint.restore(
            str(tmp_path), params2, opt2)
        assert step_no == 2
        loader2 = make_loader(state=saved["loader"])
        _, _, losses = run_steps(step2_fn, tok_sh2, params2, opt2,
                                 loader2, 3)
        if exact:
            assert losses == ref_losses, (
                "same-topology resume must stay bit-exact")
        else:
            np.testing.assert_allclose(losses, ref_losses,
                                       rtol=1e-5, atol=1e-5)


class TestResumeMetadata:
    def test_geometry_mismatch_raises(self):
        meta = checkpoint.train_metadata(
            topology.MeshAxes(dp=2), CFG, global_batch=BATCH, seq_len=SEQ)
        import dataclasses

        other = dataclasses.replace(CFG, d_model=64)
        with pytest.raises(ValueError, match="model geometry mismatch"):
            checkpoint.validate_resume_metadata(
                meta, topology.MeshAxes(dp=2), other,
                global_batch=BATCH, seq_len=SEQ)

    def test_data_stream_mismatch_raises(self):
        meta = checkpoint.train_metadata(
            topology.MeshAxes(dp=2), CFG, global_batch=BATCH, seq_len=SEQ)
        with pytest.raises(ValueError, match="data stream mismatch"):
            checkpoint.validate_resume_metadata(
                meta, topology.MeshAxes(dp=2), CFG,
                global_batch=BATCH * 2, seq_len=SEQ)

    def test_legacy_checkpoint_passes(self):
        # pre-metadata checkpoints have nothing to validate against
        assert checkpoint.validate_resume_metadata(
            {}, topology.MeshAxes(dp=2), CFG,
            global_batch=BATCH, seq_len=SEQ) is None

    def test_elastic_ladder_recorded(self):
        meta = checkpoint.train_metadata(
            topology.MeshAxes(dp=2), CFG, global_batch=BATCH, seq_len=SEQ,
            elastic={"min_chips": 2, "requested": {"tp": 2}})
        assert meta["elastic"]["min_chips"] == 2
        assert meta["mesh"]["dp"] == 2
        assert meta["model"]["d_model"] == CFG.d_model


class TestLoaderReslice:
    def test_resume_to_new_host_width_preserves_the_stream(self):
        """A loader checkpointed on 1 host and resumed on 2 hosts yields
        EXACTLY the uninterrupted stream's rows, split by host — no sample
        double-trained or skipped across the dp-width change."""
        ref = make_loader()
        for _ in range(3):
            next(ref)
        state = ref.to_dict()
        expected = [next(ref) for _ in range(2)]

        halves = [make_loader(state=state, process_index=i, process_count=2)
                  for i in range(2)]
        for step in range(2):
            merged = np.vstack([next(h) for h in halves])
            np.testing.assert_array_equal(merged, expected[step])

    def test_indivisible_host_width_rejected(self):
        state = make_loader().to_dict()
        with pytest.raises(ValueError, match="not divisible"):
            make_loader(state=state, process_index=0, process_count=3)


class TestElasticAxes:
    def test_preferences_kept_when_they_fit(self):
        axes = topology.elastic_axes(8, tp=2, sp=2, n_heads=4)
        assert (axes.dp, axes.tp, axes.sp) == (2, 2, 2)

    def test_shrinks_to_the_offered_slice(self):
        # tp=4 cannot fit 2 devices: the largest fitting divisor wins
        axes = topology.elastic_axes(2, tp=4, n_heads=4)
        assert (axes.dp, axes.tp) == (1, 2)

    def test_grow_fills_dp(self):
        axes = topology.elastic_axes(8, tp=2, n_heads=4)
        assert (axes.dp, axes.tp) == (4, 2)

    def test_head_constraint_caps_tp(self):
        # 2 heads cannot shard over tp=4 even though 4 devices exist
        axes = topology.elastic_axes(4, tp=4, n_heads=2)
        assert (axes.dp, axes.tp) == (2, 2)

    def test_batch_constraint_caps_dp_via_fsdp(self):
        # batch 2 cannot shard over dp*fsdp=4: no valid mesh at 4 devices
        # without another axis to absorb them
        with pytest.raises(ValueError, match="no valid mesh"):
            topology.elastic_axes(4, global_batch=2)
        axes = topology.elastic_axes(4, tp=2, global_batch=2, n_heads=4)
        assert (axes.dp, axes.tp) == (2, 2)

    def test_deterministic(self):
        a = topology.elastic_axes(8, tp=2, sp=2, fsdp=2, n_heads=8)
        b = topology.elastic_axes(8, tp=2, sp=2, fsdp=2, n_heads=8)
        assert a == b

    def test_pp_is_sacrificed_last(self):
        # 4 devices, pp=2 tp=2 sp=2 requested: sp gives way before tp/pp
        axes = topology.elastic_axes(4, pp=2, tp=2, sp=2, n_heads=4)
        assert (axes.pp, axes.tp, axes.sp) == (2, 2, 1)


class TestElasticCLI:
    def test_min_chips_requires_elastic(self):
        from hivedscheduler_tpu import train as train_cli

        with pytest.raises(SystemExit):
            train_cli.main(["--min-chips", "2"])

    def test_min_chips_floor_enforced(self, tmp_path):
        from hivedscheduler_tpu import train as train_cli

        with pytest.raises(SystemExit, match="elastic job floor not met"):
            train_cli.main([
                "--steps", "1", "--batch", "2", "--seq-len", "16",
                "--vocab-size", "64", "--d-model", "16", "--n-layers", "1",
                "--n-heads", "2", "--d-ff", "32",
                "--elastic", "--min-chips", "1024",
            ])

    def test_elastic_run_and_cross_topology_metadata(self, tmp_path):
        """Fast in-process cousin of the slow elastic chaos episode: one
        tiny `train --elastic` run records its derived mesh in the commit
        marker; a second run with a different tp preference resumes from
        it cleanly (the cross-topology metadata path end to end)."""
        from hivedscheduler_tpu import train as train_cli

        def args(steps, *extra):
            return [
                "--steps", str(steps), "--batch", "8", "--seq-len", "16",
                "--vocab-size", "64", "--d-model", "16", "--n-layers", "1",
                "--n-heads", "2", "--d-ff", "32", "--log-every", "100",
                "--checkpoint-dir", str(tmp_path),
                "--checkpoint-every", "1",
                "--elastic", "--min-chips", "1", *extra,
            ]

        assert train_cli.main(args(2)) == 0
        meta = checkpoint.read_metadata(str(tmp_path))
        n = len(jax.devices())
        assert meta["mesh"]["dp"] == n and meta["mesh"]["tp"] == 1
        assert meta["elastic"]["min_chips"] == 1
        # resume with a tp preference: derives a different mesh, restores
        # the dp-mesh checkpoint onto it, trains 1 more step
        assert train_cli.main(args(3, "--tp", "2")) == 0
        meta = checkpoint.read_metadata(str(tmp_path))
        assert meta["mesh"]["tp"] == 2
